"""RL core: jax policy/value networks + PPO learner update.

Reference: ``rllib/core`` — ``RLModule`` (rl_module.py:260) holds the
networks, ``Learner``/``TorchLearner`` (learner.py:111, torch_learner.py:62)
owns the optimized update. TPU-native: the module is a pytree of params with
pure apply functions; the learner update is one jitted function (minibatch
SGD inside ``lax`` loops) that runs on TPU or CPU unchanged, and scales to a
learner mesh with the same sharding machinery as ray_tpu.models.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def mlp_init(key, sizes, scale=None):
    params = []
    for i, (din, dout) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        s = scale if (scale is not None and i == len(sizes) - 2) \
            else float(np.sqrt(2.0 / din))
        params.append({
            "w": jax.random.normal(sub, (din, dout), jnp.float32) * s,
            "b": jnp.zeros((dout,), jnp.float32),
        })
    return params


def mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class PPOModule:
    """Actor-critic module for discrete action spaces."""

    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        k1, k2 = jax.random.split(key)
        return {
            "pi": mlp_init(k1, (self.obs_dim, *self.hidden, self.num_actions),
                           scale=0.01),
            "vf": mlp_init(k2, (self.obs_dim, *self.hidden, 1), scale=1.0),
        }

    @staticmethod
    def logits(params, obs):
        return mlp_apply(params["pi"], obs)

    @staticmethod
    def value(params, obs):
        return mlp_apply(params["vf"], obs)[..., 0]


class SampleBatch(NamedTuple):
    obs: np.ndarray
    actions: np.ndarray
    logprobs: np.ndarray
    values: np.ndarray
    advantages: np.ndarray
    returns: np.ndarray


def compute_gae(rewards, values, dones, last_values, gamma, lam):
    """Generalized advantage estimation over [T, N] rollouts."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    lastgaelam = np.zeros(rewards.shape[1], dtype=np.float32)
    for t in reversed(range(T)):
        nextvalue = last_values if t == T - 1 else values[t + 1]
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * nextvalue * nonterminal - values[t]
        lastgaelam = delta + gamma * lam * nonterminal * lastgaelam
        adv[t] = lastgaelam
    returns = adv + values
    return adv, returns


class PPOLearner:
    """Jitted PPO update (reference: torch_learner.py update loop)."""

    def __init__(self, module: PPOModule, lr: float = 3e-4,
                 clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.0, num_epochs: int = 4,
                 minibatch_size: int = 128, seed: int = 0):
        self.module = module
        self.optimizer = optax.adam(lr)
        self.clip = clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._make_update())
        self._rng = np.random.default_rng(seed)

    def _make_update(self):
        clip, vf_coeff, ent_coeff = self.clip, self.vf_coeff, self.entropy_coeff
        module, optimizer = self.module, self.optimizer

        def loss_fn(params, batch):
            logits = module.logits(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logprobs"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
            vf = jnp.mean((module.value(params, batch["obs"])
                           - batch["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pg + vf_coeff * vf - ent_coeff * entropy
            return total, {"policy_loss": pg, "vf_loss": vf,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        return update

    def update_from_batch(self, batch: SampleBatch) -> Dict[str, float]:
        n = len(batch.obs)
        metrics = {}
        for _ in range(self.num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n, self.minibatch_size):
                idx = perm[start:start + self.minibatch_size]
                mb = {
                    "obs": jnp.asarray(batch.obs[idx]),
                    "actions": jnp.asarray(batch.actions[idx]),
                    "logprobs": jnp.asarray(batch.logprobs[idx]),
                    "advantages": jnp.asarray(batch.advantages[idx]),
                    "returns": jnp.asarray(batch.returns[idx]),
                }
                self.params, self.opt_state, metrics = self._update(
                    self.params, self.opt_state, mb)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)


def vtrace(target_logp, behavior_logp, rewards, dones, values,
           bootstrap_value, gamma, rho_bar=1.0, c_bar=1.0):
    """V-trace off-policy corrected value targets + policy-gradient
    advantages (reference: IMPALA — ``rllib/algorithms/impala``; the
    algorithm of Espeholt et al. 2018, implemented here as a jit-friendly
    reversed ``lax.scan`` over the trajectory instead of a Python loop).

    All inputs are [T, N]; ``bootstrap_value`` is [N]. Returns
    ``(vs, pg_advantages)``, both [T, N], with gradients stopped.
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    cs = jnp.minimum(c_bar, rhos)
    discounts = gamma * (1.0 - dones)
    next_values = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * next_values - values)

    def backward(acc, t):
        acc = deltas[t] + discounts[t] * cs[t] * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        jnp.arange(values.shape[0] - 1, -1, -1))
    vs_minus_v = vs_minus_v[::-1]
    vs = values + vs_minus_v
    next_vs = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + discounts * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner:
    """Jitted IMPALA learner: actor-critic update on V-trace-corrected
    trajectories collected by decoupled (stale-policy) env runners
    (reference: ``rllib/algorithms/impala`` — the learner half of the
    decoupled actor/learner architecture; here the update is one jitted
    function and gradients split from application so a LearnerGroup can
    allreduce across learner actors)."""

    def __init__(self, module: PPOModule, lr: float = 5e-4,
                 gamma: float = 0.99, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, rho_bar: float = 1.0,
                 c_bar: float = 1.0, seed: int = 0,
                 clip_param: Optional[float] = None):
        """``clip_param`` switches the policy loss from IMPALA's plain
        V-trace policy gradient to APPO's PPO-style clipped surrogate
        over the V-trace advantages (reference:
        ``rllib/algorithms/appo/appo.py`` — async PPO = the IMPALA
        architecture with the clipped surrogate objective)."""
        self.module = module
        self.optimizer = optax.adam(lr)
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        mod, g, vf_c, ent_c = module, gamma, vf_coeff, entropy_coeff
        clip = clip_param

        def loss_fn(params, b):
            T, N = b["actions"].shape
            flat_obs = b["obs"].reshape((T * N,) + b["obs"].shape[2:])
            logits = mod.logits(params, flat_obs).reshape((T, N, -1))
            values = mod.value(params, flat_obs).reshape((T, N))
            bootstrap = mod.value(params, b["bootstrap_obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, b["actions"][..., None], axis=-1)[..., 0]
            vs, pg_adv = vtrace(logp, b["behavior_logp"], b["rewards"],
                                b["dones"], values, bootstrap, g,
                                rho_bar, c_bar)
            if clip is None:
                pg_loss = -jnp.mean(logp * pg_adv)
            else:
                # APPO: clipped surrogate against the BEHAVIOR policy
                # (the async analog of PPO's old policy).
                ratio = jnp.exp(logp - b["behavior_logp"])
                surr = jnp.minimum(
                    ratio * pg_adv,
                    jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * pg_adv)
                pg_loss = -jnp.mean(surr)
            vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pg_loss + vf_c * vf_loss - ent_c * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_rho": jnp.mean(
                               jnp.exp(logp - b["behavior_logp"]))}

        self._grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return optax.apply_updates(params, updates), opt_state

        self._apply_fn = jax.jit(apply_fn)

    @staticmethod
    def _to_device(traj: Dict[str, np.ndarray]) -> Dict[str, Any]:
        return {k: jnp.asarray(v) for k, v in traj.items()}

    def compute_gradients(self, traj: Dict[str, np.ndarray]):
        (loss, metrics), grads = self._grad_fn(self.params,
                                               self._to_device(traj))
        metrics = {k: float(v) for k, v in metrics.items()}
        metrics["total_loss"] = float(loss)
        return grads, metrics

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads)

    def update_from_batch(self, traj) -> Dict[str, float]:
        grads, metrics = self.compute_gradients(traj)
        self.apply_gradients(grads)
        return metrics

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class DQNModule:
    """Q-network module for discrete action spaces (reference:
    ``rllib/algorithms/dqn`` default RLModule)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        return {"q": mlp_init(key, (self.obs_dim, *self.hidden,
                                    self.num_actions), scale=0.01)}

    @staticmethod
    def q_values(params, obs):
        return mlp_apply(params["q"], obs)


class Transition(NamedTuple):
    obs: np.ndarray
    actions: np.ndarray
    rewards: np.ndarray
    next_obs: np.ndarray
    dones: np.ndarray


class ReplayBuffer:
    """Uniform ring-buffer replay (reference:
    ``rllib/utils/replay_buffers/replay_buffer.py``). ``action_dim=None``
    stores discrete int actions (DQN); an int stores float action vectors
    (SAC/continuous control)."""

    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: Optional[int] = None):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        if action_dim is None:
            self.actions = np.zeros((capacity,), np.int64)
        else:
            self.actions = np.zeros((capacity, action_dim), np.float32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self.idx = 0
        self.size = 0
        self._rng = np.random.default_rng(seed)

    def add(self, batch: Transition) -> None:
        n = len(batch.obs)
        ix = (self.idx + np.arange(n)) % self.capacity
        self.obs[ix] = batch.obs
        self.actions[ix] = batch.actions
        self.rewards[ix] = batch.rewards
        self.next_obs[ix] = batch.next_obs
        self.dones[ix] = batch.dones
        self.idx = int((self.idx + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def sample(self, batch_size: int) -> Transition:
        ix = self._rng.integers(0, self.size, size=batch_size)
        return Transition(self.obs[ix], self.actions[ix], self.rewards[ix],
                          self.next_obs[ix], self.dones[ix])


class SACModule:
    """Squashed-Gaussian actor + twin Q critics for continuous action
    spaces (reference: ``rllib/algorithms/sac`` default RLModule)."""

    LOG_STD_MIN, LOG_STD_MAX = -10.0, 2.0

    def __init__(self, obs_dim: int, action_dim: int, hidden=(128, 128)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        kp, k1, k2 = jax.random.split(key, 3)
        return {
            "pi": mlp_init(kp, (self.obs_dim, *self.hidden,
                                2 * self.action_dim), scale=0.01),
            "q1": mlp_init(k1, (self.obs_dim + self.action_dim,
                                *self.hidden, 1), scale=1.0),
            "q2": mlp_init(k2, (self.obs_dim + self.action_dim,
                                *self.hidden, 1), scale=1.0),
        }

    def pi_dist(self, params, obs):
        out = mlp_apply(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mean, log_std

    def sample_action(self, params, obs, key):
        """Reparameterized tanh-squashed sample: (action in (-1,1), logp)."""
        mean, log_std = self.pi_dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        action = jnp.tanh(pre)
        # logp with tanh change-of-variables (SAC appendix C).
        logp = jnp.sum(
            -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            - jnp.log(1 - action ** 2 + 1e-6), axis=-1)
        return action, logp

    @staticmethod
    def q_value(params, name, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        return mlp_apply(params[name], x)[..., 0]


class SACLearner:
    """Jitted soft actor-critic update (reference:
    ``rllib/algorithms/sac`` losses): twin-critic TD with target-network
    polyak averaging, reparameterized actor loss, and automatic
    temperature tuning toward -|A| target entropy. Gradients are computed
    jointly over {pi, q1, q2, log_alpha} and applied in one optimizer, so
    the LearnerGroup's flatten-allreduce works unchanged."""

    def __init__(self, module: SACModule, lr: float = 3e-4,
                 gamma: float = 0.99, tau: float = 0.005, seed: int = 0):
        self.module = module
        self.optimizer = optax.adam(lr)
        self.gamma = gamma
        self.tau = tau
        net = module.init(jax.random.PRNGKey(seed))
        self.params = {**net, "log_alpha": jnp.zeros(())}
        self.target_params = jax.tree.map(jnp.asarray,
                                          {"q1": net["q1"],
                                           "q2": net["q2"]})
        self.opt_state = self.optimizer.init(self.params)
        self._key = jax.random.PRNGKey(seed + 1)
        target_entropy = -float(module.action_dim)
        mod, g = module, gamma

        def loss_fn(params, target, b, key):
            ka, kn = jax.random.split(key)
            alpha = jnp.exp(params["log_alpha"])
            # Critic target: r + γ(1-d)(min target-Q(s',ã') - α logπ(ã'|s'))
            next_a, next_logp = mod.sample_action(params, b["next_obs"], kn)
            next_q = jnp.minimum(
                mod.q_value(target, "q1", b["next_obs"], next_a),
                mod.q_value(target, "q2", b["next_obs"], next_a))
            y = b["rewards"] + g * (1.0 - b["dones"]) * \
                jax.lax.stop_gradient(
                    next_q - jax.lax.stop_gradient(alpha) * next_logp)
            q1 = mod.q_value(params, "q1", b["obs"], b["actions"])
            q2 = mod.q_value(params, "q2", b["obs"], b["actions"])
            critic_loss = jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)
            # Actor: α logπ(ã|s) - min Q(s,ã) with critics frozen.
            a_new, logp = mod.sample_action(params, b["obs"], ka)
            frozen_q = jax.lax.stop_gradient(
                {"q1": params["q1"], "q2": params["q2"]})
            q_pi = jnp.minimum(
                mod.q_value(frozen_q, "q1", b["obs"], a_new),
                mod.q_value(frozen_q, "q2", b["obs"], a_new))
            actor_loss = jnp.mean(
                jax.lax.stop_gradient(alpha) * logp - q_pi)
            # Temperature: drive entropy toward -|A|.
            alpha_loss = -jnp.mean(
                params["log_alpha"] *
                jax.lax.stop_gradient(logp + target_entropy))
            total = critic_loss + actor_loss + alpha_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "alpha": alpha,
                           "entropy": -jnp.mean(logp)}

        self._grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

        def apply_fn(params, opt_state, target, grads):
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, p: (1 - self.tau) * t + self.tau * p,
                target, {"q1": params["q1"], "q2": params["q2"]})
            return params, opt_state, target

        self._apply_fn = jax.jit(apply_fn)

    @staticmethod
    def _to_batch(t: Transition) -> Dict[str, Any]:
        return {"obs": jnp.asarray(t.obs),
                "actions": jnp.asarray(t.actions),
                "rewards": jnp.asarray(t.rewards),
                "next_obs": jnp.asarray(t.next_obs),
                "dones": jnp.asarray(t.dones)}

    def compute_gradients(self, t: Transition):
        self._key, sub = jax.random.split(self._key)
        (loss, metrics), grads = self._grad_fn(
            self.params, self.target_params, self._to_batch(t), sub)
        metrics = {k: float(v) for k, v in metrics.items()}
        metrics["total_loss"] = float(loss)
        return grads, metrics

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state, self.target_params = self._apply_fn(
            self.params, self.opt_state, self.target_params, grads)

    def update_from_batch(self, t: Transition) -> Dict[str, float]:
        grads, metrics = self.compute_gradients(t)
        self.apply_gradients(grads)
        return metrics

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class DQNLearner:
    """Jitted double-DQN learner (reference:
    ``rllib/algorithms/dqn/torch/dqn_torch_learner.py`` loss). The
    gradient computation and application are split so a LearnerGroup can
    allreduce gradients between them (multi-learner data parallelism)."""

    def __init__(self, module: DQNModule, lr: float = 5e-4,
                 gamma: float = 0.99, target_update_freq: int = 200,
                 seed: int = 0):
        self.module = module
        self.optimizer = optax.adam(lr)
        self.gamma = gamma
        self.target_update_freq = target_update_freq
        self.params = module.init(jax.random.PRNGKey(seed))
        self.target_params = jax.tree.map(jnp.asarray, self.params)
        self.opt_state = self.optimizer.init(self.params)
        self.steps = 0
        module_q, gamma_c = module.q_values, gamma

        def loss_fn(params, target_params, b):
            q = module_q(params, b["obs"])
            q_taken = jnp.take_along_axis(q, b["actions"][:, None],
                                          axis=1)[:, 0]
            # Double DQN: online net picks the action, target net scores it.
            next_a = jnp.argmax(module_q(params, b["next_obs"]), axis=-1)
            next_q = jnp.take_along_axis(
                module_q(target_params, b["next_obs"]), next_a[:, None],
                axis=1)[:, 0]
            y = b["rewards"] + gamma_c * (1.0 - b["dones"]) * \
                jax.lax.stop_gradient(next_q)
            td = q_taken - y
            loss = jnp.mean(optax.huber_loss(td))
            return loss, {"td_error_mean": jnp.mean(jnp.abs(td))}

        self._grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return optax.apply_updates(params, updates), opt_state

        self._apply_fn = jax.jit(apply_fn)

    @staticmethod
    def _to_batch(t: Transition) -> Dict[str, Any]:
        return {"obs": jnp.asarray(t.obs),
                "actions": jnp.asarray(t.actions),
                "rewards": jnp.asarray(t.rewards),
                "next_obs": jnp.asarray(t.next_obs),
                "dones": jnp.asarray(t.dones)}

    def compute_gradients(self, t: Transition):
        (loss, metrics), grads = self._grad_fn(
            self.params, self.target_params, self._to_batch(t))
        metrics = {k: float(v) for k, v in metrics.items()}
        metrics["loss"] = float(loss)
        return grads, metrics

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads)
        self.steps += 1
        if self.steps % self.target_update_freq == 0:
            self.target_params = jax.tree.map(jnp.asarray, self.params)

    def update_from_batch(self, t: Transition) -> Dict[str, float]:
        grads, metrics = self.compute_gradients(t)
        self.apply_gradients(grads)
        return metrics

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)
