"""RL core: jax policy/value networks + PPO learner update.

Reference: ``rllib/core`` — ``RLModule`` (rl_module.py:260) holds the
networks, ``Learner``/``TorchLearner`` (learner.py:111, torch_learner.py:62)
owns the optimized update. TPU-native: the module is a pytree of params with
pure apply functions; the learner update is one jitted function (minibatch
SGD inside ``lax`` loops) that runs on TPU or CPU unchanged, and scales to a
learner mesh with the same sharding machinery as ray_tpu.models.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def mlp_init(key, sizes, scale=None):
    params = []
    for i, (din, dout) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        s = scale if (scale is not None and i == len(sizes) - 2) \
            else float(np.sqrt(2.0 / din))
        params.append({
            "w": jax.random.normal(sub, (din, dout), jnp.float32) * s,
            "b": jnp.zeros((dout,), jnp.float32),
        })
    return params


def mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class PPOModule:
    """Actor-critic module for discrete action spaces."""

    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        k1, k2 = jax.random.split(key)
        return {
            "pi": mlp_init(k1, (self.obs_dim, *self.hidden, self.num_actions),
                           scale=0.01),
            "vf": mlp_init(k2, (self.obs_dim, *self.hidden, 1), scale=1.0),
        }

    @staticmethod
    def logits(params, obs):
        return mlp_apply(params["pi"], obs)

    @staticmethod
    def value(params, obs):
        return mlp_apply(params["vf"], obs)[..., 0]


class SampleBatch(NamedTuple):
    obs: np.ndarray
    actions: np.ndarray
    logprobs: np.ndarray
    values: np.ndarray
    advantages: np.ndarray
    returns: np.ndarray


def compute_gae(rewards, values, dones, last_values, gamma, lam):
    """Generalized advantage estimation over [T, N] rollouts."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    lastgaelam = np.zeros(rewards.shape[1], dtype=np.float32)
    for t in reversed(range(T)):
        nextvalue = last_values if t == T - 1 else values[t + 1]
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * nextvalue * nonterminal - values[t]
        lastgaelam = delta + gamma * lam * nonterminal * lastgaelam
        adv[t] = lastgaelam
    returns = adv + values
    return adv, returns


class PPOLearner:
    """Jitted PPO update (reference: torch_learner.py update loop)."""

    def __init__(self, module: PPOModule, lr: float = 3e-4,
                 clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.0, num_epochs: int = 4,
                 minibatch_size: int = 128, seed: int = 0):
        self.module = module
        self.optimizer = optax.adam(lr)
        self.clip = clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._make_update())
        self._rng = np.random.default_rng(seed)

    def _make_update(self):
        clip, vf_coeff, ent_coeff = self.clip, self.vf_coeff, self.entropy_coeff
        module, optimizer = self.module, self.optimizer

        def loss_fn(params, batch):
            logits = module.logits(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logprobs"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
            vf = jnp.mean((module.value(params, batch["obs"])
                           - batch["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pg + vf_coeff * vf - ent_coeff * entropy
            return total, {"policy_loss": pg, "vf_loss": vf,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        return update

    def update_from_batch(self, batch: SampleBatch) -> Dict[str, float]:
        n = len(batch.obs)
        metrics = {}
        for _ in range(self.num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n, self.minibatch_size):
                idx = perm[start:start + self.minibatch_size]
                mb = {
                    "obs": jnp.asarray(batch.obs[idx]),
                    "actions": jnp.asarray(batch.actions[idx]),
                    "logprobs": jnp.asarray(batch.logprobs[idx]),
                    "advantages": jnp.asarray(batch.advantages[idx]),
                    "returns": jnp.asarray(batch.returns[idx]),
                }
                self.params, self.opt_state, metrics = self._update(
                    self.params, self.opt_state, mb)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
