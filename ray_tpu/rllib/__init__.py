"""ray_tpu.rllib: reinforcement learning (reference: ``rllib/``)."""

from ray_tpu.rllib.core import (
    DQNLearner,
    DQNModule,
    PPOLearner,
    PPOModule,
    ReplayBuffer,
    SampleBatch,
    Transition,
    compute_gae,
)
from ray_tpu.rllib.core import ImpalaLearner, SACLearner, SACModule, vtrace
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env_runner import (
    ContinuousEnvRunner,
    EnvRunnerGroup,
    SingleAgentEnvRunner,
    TrajectoryEnvRunner,
    TransitionEnvRunner,
)
from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.learner_group import LearnerGroup
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.sac import SAC, SACConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = [
    "ContinuousEnvRunner", "DQN", "DQNConfig", "DQNLearner", "DQNModule",
    "EnvRunnerGroup", "FaultTolerantActorManager", "APPO", "APPOConfig", "IMPALA", "IMPALAConfig",
    "ImpalaLearner", "LearnerGroup", "MultiAgentEnv", "MultiAgentEnvRunner",
    "MultiAgentPPO", "MultiAgentPPOConfig", "PPO", "PPOConfig", "PPOLearner",
    "PPOModule", "ReplayBuffer", "SAC", "SACConfig", "SACLearner",
    "SACModule", "SampleBatch", "SingleAgentEnvRunner",
    "TrajectoryEnvRunner", "Transition", "TransitionEnvRunner",
    "compute_gae", "vtrace",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("rllib")
del _rlu
