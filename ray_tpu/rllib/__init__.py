"""ray_tpu.rllib: reinforcement learning (reference: ``rllib/``)."""

from ray_tpu.rllib.core import PPOLearner, PPOModule, SampleBatch, compute_gae
from ray_tpu.rllib.env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = [
    "EnvRunnerGroup", "PPO", "PPOConfig", "PPOLearner", "PPOModule",
    "SampleBatch", "SingleAgentEnvRunner", "compute_gae",
]
