"""LearnerGroup: data-parallel learners with collective gradient allreduce.

Reference: ``rllib/core/learner/learner_group.py:80`` — N learner actors
each hold a replica of the module; every update computes gradients on a
shard of the batch, allreduces them (the reference uses NCCL; here the
rendezvous-actor CPU collective — on TPU pods the learners would instead
share one jitted update over a device mesh), and applies locally, so
weights stay bit-identical across learners without a broadcast step.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


class _LearnerActor:
    def __init__(self, learner_builder, rank: int, world_size: int,
                 group_name: str):
        # Same seed inside the builder -> identical initial replicas.
        self.learner = learner_builder()
        self.rank = rank
        self.world_size = world_size
        self.group = None
        if world_size > 1:
            from ray_tpu.util.collective import init_collective_group

            self.group = init_collective_group(world_size, rank, group_name)

    def update_many(self, shards) -> Dict[str, float]:
        """Apply a sequence of update batches in one RPC (off-policy
        algorithms do tens of replay updates per rollout; one RPC per
        update would dominate the step time). Collective ordering stays
        aligned across learners because every learner receives the same
        number of shards in the same order."""
        metrics: Dict[str, float] = {}
        for shard in shards:
            metrics = self.update(shard)
        return metrics

    def update(self, shard) -> Dict[str, float]:
        import jax

        grads, metrics = self.learner.compute_gradients(shard)
        if self.group is not None:
            # ONE allreduce per update: gradients are flattened into a
            # single vector (bucketing), not reduced leaf-by-leaf — each
            # collective round costs rendezvous RPCs, so per-leaf rounds
            # would multiply latency by the leaf count (reference analog:
            # gradient bucketing in DDP/NCCL allreduce).
            leaves, treedef = jax.tree.flatten(grads)
            arrs = [np.asarray(leaf) for leaf in leaves]
            flat = np.concatenate([a.ravel() for a in arrs])
            reduced = self.group.allreduce(flat) / self.world_size
            out, off = [], 0
            for a in arrs:
                out.append(reduced[off:off + a.size].reshape(a.shape)
                           .astype(a.dtype))
                off += a.size
            grads = jax.tree.unflatten(treedef, out)
        self.learner.apply_gradients(grads)
        return metrics

    def get_weights(self):
        from ray_tpu._private import chaos

        weights = self.learner.get_weights()
        if chaos.enabled():
            # Cooperative divergence fault: a matched rank hands back
            # weights nudged by eps. The learner's OWN replica stays
            # intact — the fault is in what it reports, exactly the kind
            # of silent skew the group-level bit-identity check targets.
            directive = chaos.inject("learner_weights", rank=self.rank)
            if directive and "perturb" in directive:
                import jax

                eps = directive["perturb"]
                weights = jax.tree.map(
                    lambda a: np.asarray(a) + np.asarray(eps,
                                                         np.asarray(a).dtype),
                    weights)
        return weights

    def set_weights(self, weights):
        self.learner.set_weights(weights)
        return True

    def ping(self):
        return True


class LearnerGroup:
    """Drives N learner actors as one logical learner."""

    def __init__(self, learner_builder, num_learners: int = 1):
        self.num_learners = max(num_learners, 1)
        group_name = f"learner_group_{uuid.uuid4().hex[:8]}"
        cls = ray_tpu.remote(_LearnerActor)
        self.learners = [
            cls.remote(learner_builder, rank, self.num_learners, group_name)
            for rank in range(self.num_learners)
        ]
        ray_tpu.get([a.ping.remote() for a in self.learners], timeout=120)

    @staticmethod
    def _shard(batch, n: int) -> List[Any]:
        if n == 1:
            return [batch]
        if isinstance(batch, dict):
            # [T, N] trajectory dict (IMPALA): shard along the env axis;
            # bootstrap_obs is [N]-leading.
            size = batch["actions"].shape[1]
            if size < n:
                # Fewer envs than learners: every learner grads the full
                # batch — the allreduce average then equals a single
                # learner's update (zero-width shards would reshape-crash
                # and NaN the mean).
                return [batch] * n
            bounds = [size * i // n for i in range(n + 1)]
            out = []
            for i in range(n):
                lo, hi = bounds[i], bounds[i + 1]
                out.append({k: (v[lo:hi] if k == "bootstrap_obs"
                                else v[:, lo:hi])
                            for k, v in batch.items()})
            return out
        size = len(batch.obs)
        if size < n:
            return [batch] * n
        bounds = [size * i // n for i in range(n + 1)]
        return [type(batch)(*[f[bounds[i]:bounds[i + 1]] for f in batch])
                for i in range(n)]

    def update(self, batch) -> Dict[str, float]:
        """One synchronized update over all learners; returns rank-0
        metrics (identical shards -> near-identical metrics)."""
        shards = self._shard(batch, self.num_learners)
        metrics = ray_tpu.get(
            [a.update.remote(s) for a, s in zip(self.learners, shards)],
            timeout=300)
        return metrics[0]

    def update_many(self, batches) -> Dict[str, float]:
        """Apply many update batches with ONE RPC per learner (replay-heavy
        algorithms like SAC/DQN do dozens of updates per rollout)."""
        per_learner = [[] for _ in range(self.num_learners)]
        for batch in batches:
            for i, shard in enumerate(self._shard(batch,
                                                  self.num_learners)):
                per_learner[i].append(shard)
        metrics = ray_tpu.get(
            [a.update_many.remote(s)
             for a, s in zip(self.learners, per_learner)],
            timeout=600)
        return metrics[0]

    def get_weights(self):
        """Weights of the logical learner.

        The allreduce invariant makes every learner's replica
        bit-identical, so one read (learner 0) suffices on the fast path.
        In debug/chaos mode that invariant is VERIFIED, not assumed: all
        learners are read and compared leaf-by-leaf bitwise, so a
        silently diverged replica (lost collective round, perturbed
        reporter) fails loudly here instead of training on skewed
        weights. Enable via ``RAY_TPU_RL_DEBUG=1`` or any active chaos
        plan."""
        import os

        from ray_tpu._private import chaos

        if not (chaos.enabled() or os.environ.get("RAY_TPU_RL_DEBUG")):
            return ray_tpu.get(self.learners[0].get_weights.remote(),
                               timeout=120)
        all_weights = self.get_all_weights()
        self._check_bit_identity(all_weights)
        return all_weights[0]

    def _check_bit_identity(self, all_weights: List[Any]) -> None:
        import jax

        from ray_tpu._private import events as _events

        ref_leaves, ref_treedef = jax.tree.flatten(all_weights[0])
        for rank, weights in enumerate(all_weights[1:], start=1):
            leaves, treedef = jax.tree.flatten(weights)
            if treedef != ref_treedef:
                raise RuntimeError(
                    f"learner {rank} weight tree structure diverged from "
                    f"learner 0")
            for i, (a, b) in enumerate(zip(ref_leaves, leaves)):
                a, b = np.asarray(a), np.asarray(b)
                if a.shape != b.shape or a.dtype != b.dtype \
                        or a.tobytes() != b.tobytes():
                    _events.emit("rl.learner_divergence",
                                 subject={"group": "learners"},
                                 rank=rank, leaf=i)
                    raise RuntimeError(
                        f"learner {rank} weights diverged from learner 0 "
                        f"at leaf {i} (shape {b.shape}, dtype {b.dtype}) "
                        f"— the allreduce bit-identity invariant is "
                        f"broken")

    def get_all_weights(self) -> List[Any]:
        return ray_tpu.get([a.get_weights.remote() for a in self.learners],
                           timeout=120)

    def set_weights(self, weights) -> None:
        ray_tpu.get([a.set_weights.remote(weights) for a in self.learners],
                    timeout=120)
