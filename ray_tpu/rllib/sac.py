"""SAC: soft actor-critic for continuous action spaces.

Reference: ``rllib/algorithms/sac`` — off-policy replay, twin critics with
polyak target networks, reparameterized squashed-Gaussian actor, automatic
temperature tuning. The loop mirrors :mod:`ray_tpu.rllib.dqn`: continuous
env runners fill a uniform replay buffer, a
:class:`~ray_tpu.rllib.learner_group.LearnerGroup` of SAC learners applies
allreduced updates, and fresh weights broadcast back each iteration.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core import ReplayBuffer
from ray_tpu.rllib.env_runner import ContinuousEnvRunner
from ray_tpu.rllib.learner_group import LearnerGroup


@dataclasses.dataclass
class SACConfig:
    env: Optional[str] = None
    env_creator: Optional[Callable] = None
    num_env_runners: int = 1
    num_envs_per_env_runner: int = 1
    rollout_fragment_length: int = 64
    lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005
    buffer_size: int = 100_000
    train_batch_size: int = 128
    num_updates_per_iteration: int = 32
    learning_starts: int = 500
    num_learners: int = 1
    hidden_sizes: tuple = (128, 128)
    seed: int = 0

    # -- fluent builder (reference AlgorithmConfig style) ------------------
    def environment(self, env: Optional[str] = None, *,
                    env_creator: Optional[Callable] = None) -> "SACConfig":
        self.env = env
        self.env_creator = env_creator
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "SACConfig":
        for k, v in dict(num_env_runners=num_env_runners,
                         num_envs_per_env_runner=num_envs_per_env_runner,
                         rollout_fragment_length=rollout_fragment_length
                         ).items():
            if v is not None:
                setattr(self, k, v)
        return self

    def training(self, **kwargs) -> "SACConfig":
        known = {f.name for f in dataclasses.fields(self)}
        bad = set(kwargs) - known
        if bad:
            raise ValueError(f"Unknown SAC training options: {sorted(bad)}")
        for k, v in kwargs.items():
            if v is not None:
                setattr(self, k, v)
        return self

    def learners(self, num_learners: Optional[int] = None) -> "SACConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def build(self) -> "SAC":
        return SAC(self)


def _resolve_env(config) -> Callable:
    if config.env_creator is not None:
        return config.env_creator
    if config.env is None:
        raise ValueError("SACConfig needs .environment(env=...) or "
                         "env_creator")
    import gymnasium as gym

    name = config.env
    return lambda: gym.make(name)


class SAC:
    def __init__(self, config: SACConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        creator = _resolve_env(config)
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        action_dim = int(np.prod(probe.action_space.shape))
        probe.close()
        module_spec = {"obs_dim": obs_dim, "action_dim": action_dim,
                       "hidden": tuple(config.hidden_sizes)}
        cfg = config

        def builder():
            from ray_tpu.rllib.core import SACLearner, SACModule

            return SACLearner(SACModule(**module_spec), lr=cfg.lr,
                              gamma=cfg.gamma, tau=cfg.tau, seed=cfg.seed)

        self.learner_group = LearnerGroup(builder,
                                          num_learners=config.num_learners)
        runner_cls = ray_tpu.remote(ContinuousEnvRunner)
        self.runners = [
            runner_cls.remote(creator, module_spec,
                              config.num_envs_per_env_runner, seed)
            for seed in range(config.num_env_runners)
        ]
        self.buffer = ReplayBuffer(config.buffer_size, obs_dim,
                                   seed=config.seed, action_dim=action_dim)
        self.iteration = 0
        self._returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One iteration: sample -> replay -> N learner updates -> sync."""
        c = self.config
        t0 = time.monotonic()
        # Runners only sample the policy: shipping the twin critics +
        # temperature too would ~3x the broadcast payload for nothing.
        weights = {"pi": self.learner_group.get_weights()["pi"]}
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners],
                    timeout=120)
        sampled = ray_tpu.get(
            [r.sample.remote(c.rollout_fragment_length)
             for r in self.runners], timeout=300)
        episode_returns: List[float] = []
        for transitions, finished in sampled:
            self.buffer.add(transitions)
            episode_returns.extend(finished)
        self._returns.extend(episode_returns)
        self._returns = self._returns[-100:]
        metrics: Dict[str, float] = {}
        if self.buffer.size >= max(c.learning_starts, c.train_batch_size):
            batches = [self.buffer.sample(c.train_batch_size)
                       for _ in range(c.num_updates_per_iteration)]
            metrics = self.learner_group.update_many(batches)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "buffer_size": self.buffer.size,
            "episode_return_mean": (float(np.mean(self._returns))
                                    if self._returns else float("nan")),
            "episodes_this_iter": len(episode_returns),
            "time_this_iter_s": time.monotonic() - t0,
            **metrics,
        }

    def get_weights(self):
        return self.learner_group.get_weights()

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        for a in self.learner_group.learners:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
