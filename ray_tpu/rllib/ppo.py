"""PPO algorithm (reference: ``rllib/algorithms/ppo/ppo.py:362``).

``PPOConfig`` is the AlgorithmConfig-style builder
(environment/env_runners/training fluent methods); ``PPO.train()`` is one
iteration of SURVEY.md §3.6's loop: EnvRunnerGroup.sample → GAE → jitted
learner update → weight broadcast → metrics reduce.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core import PPOLearner, PPOModule, SampleBatch
from ray_tpu.rllib.env_runner import EnvRunnerGroup


@dataclasses.dataclass
class PPOConfig:
    env: Optional[str] = None
    env_creator: Optional[Callable] = None
    num_env_runners: int = 2
    num_envs_per_env_runner: int = 4
    rollout_fragment_length: int = 64
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.0
    num_epochs: int = 4
    minibatch_size: int = 128
    hidden_sizes: tuple = (64, 64)
    seed: int = 0

    # -- fluent builder (reference AlgorithmConfig style) ------------------
    def environment(self, env: Optional[str] = None, *,
                    env_creator: Optional[Callable] = None) -> "PPOConfig":
        self.env = env
        self.env_creator = env_creator
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "PPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 clip_param: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 num_epochs: Optional[int] = None,
                 minibatch_size: Optional[int] = None,
                 hidden_sizes: Optional[tuple] = None) -> "PPOConfig":
        for k, v in dict(lr=lr, gamma=gamma, clip_param=clip_param,
                         entropy_coeff=entropy_coeff, num_epochs=num_epochs,
                         minibatch_size=minibatch_size,
                         hidden_sizes=hidden_sizes).items():
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    def __init__(self, config: PPOConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        creator = config.env_creator
        if creator is None:
            env_name = config.env

            def creator(name=env_name):
                import gymnasium as gym

                return gym.make(name)
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        probe.close()
        module_spec = {"obs_dim": obs_dim, "num_actions": num_actions,
                       "hidden": config.hidden_sizes}
        self.learner = PPOLearner(
            PPOModule(**module_spec), lr=config.lr, clip=config.clip_param,
            vf_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff,
            num_epochs=config.num_epochs,
            minibatch_size=config.minibatch_size, seed=config.seed)
        self.runner_group = EnvRunnerGroup(
            creator, module_spec, config.num_env_runners,
            config.num_envs_per_env_runner, config.gamma, config.lambda_)
        self.iteration = 0
        self._recent_returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: PPO.training_step :388)."""
        t0 = time.perf_counter()
        self.runner_group.sync_weights(self.learner.get_weights())
        batches, episode_returns = self.runner_group.sample(
            self.config.rollout_fragment_length)
        if not batches:
            return {"training_iteration": self.iteration}
        merged = SampleBatch(*[
            np.concatenate([getattr(b, f) for b in batches])
            for f in SampleBatch._fields])
        learner_metrics = self.learner.update_from_batch(merged)
        self.iteration += 1
        self._recent_returns.extend(episode_returns)
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (float(np.mean(self._recent_returns))
                       if self._recent_returns else float("nan"))
        steps = len(merged.obs)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_env_steps_sampled": steps,
            "env_steps_per_sec": steps / (time.perf_counter() - t0),
            **{f"learner/{k}": v for k, v in learner_metrics.items()},
        }

    def get_policy_weights(self):
        return self.learner.get_weights()

    def save_checkpoint(self, path: str):
        from ray_tpu.train.checkpoint import save_pytree

        save_pytree({"params": self.learner.params,
                     "opt_state": self.learner.opt_state}, path)

    def restore_checkpoint(self, path: str):
        from ray_tpu.train.checkpoint import load_pytree

        state = load_pytree(path)
        self.learner.set_weights(state["params"])

    def stop(self):
        for r in self.runner_group.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
