"""Env runners: distributed rollout collection.

Reference: ``rllib/env/single_agent_env_runner.py:65`` (``sample`` :140 —
vectorized gymnasium envs stepped with the current policy) and
``EnvRunnerGroup`` (env_runner_group.py:71) with the fault-tolerant actor
manager (utils/actor_manager.py:198): dead runners are dropped from a sample
round and respawned.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.core import PPOModule, SampleBatch, compute_gae


def _record_weights_version(runner, version) -> int:
    """Stamp a runner with the version of the weights it just received.

    ``None`` auto-increments (legacy callers that don't version their
    broadcasts still get a monotone counter); explicit versions come from
    EnvRunnerGroup so a respawned runner reports the version it was
    re-synced with, not a reset-to-zero counter."""
    if version is None:
        runner.weights_version = getattr(runner, "weights_version", 0) + 1
    else:
        runner.weights_version = int(version)
    return runner.weights_version


class SingleAgentEnvRunner:
    def __init__(self, env_creator: Callable, module_spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0,
                 gamma: float = 0.99, lam: float = 0.95):
        import gymnasium as gym
        import jax

        self.envs = gym.vector.SyncVectorEnv(
            [lambda i=i: env_creator() for i in range(num_envs)])
        self.num_envs = num_envs
        self.gamma = gamma
        self.lam = lam
        self.module = PPOModule(**module_spec)
        self.params = None
        self.rng = np.random.default_rng(seed)
        self._jax = jax
        self._forward = jax.jit(
            lambda p, o: (jax.nn.log_softmax(self.module.logits(p, o)),
                          self.module.value(p, o)))
        self.obs, _ = self.envs.reset(seed=seed)
        self._episode_returns = np.zeros(num_envs, dtype=np.float64)
        self._finished_returns: List[float] = []

    def set_weights(self, weights, version=None):
        import jax.numpy as jnp

        self.params = self._jax.tree.map(jnp.asarray, weights)
        return _record_weights_version(self, version)

    def get_weights_version(self) -> int:
        return getattr(self, "weights_version", 0)

    def sample(self, num_steps: int) -> Tuple[SampleBatch, List[float]]:
        """Collect ``num_steps`` per env; returns batch + episode returns."""
        T, N = num_steps, self.num_envs
        obs_buf = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        act_buf = np.zeros((T, N), np.int64)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)

        for t in range(T):
            logp_all, values = self._forward(self.params,
                                             self.obs.astype(np.float32))
            logp_all = np.asarray(logp_all)
            probs = np.exp(logp_all)
            probs /= probs.sum(-1, keepdims=True)
            actions = np.array([self.rng.choice(len(p), p=p) for p in probs])
            obs_buf[t] = self.obs
            act_buf[t] = actions
            logp_buf[t] = logp_all[np.arange(N), actions]
            val_buf[t] = np.asarray(values)
            self.obs, rewards, terms, truncs, _ = self.envs.step(actions)
            dones = np.logical_or(terms, truncs)
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._episode_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._finished_returns.append(self._episode_returns[i])
                    self._episode_returns[i] = 0.0

        _, last_values = self._forward(self.params,
                                       self.obs.astype(np.float32))
        adv, ret = compute_gae(rew_buf, val_buf, done_buf,
                               np.asarray(last_values), self.gamma, self.lam)
        flat = lambda x: x.reshape((-1,) + x.shape[2:])  # noqa: E731
        batch = SampleBatch(
            obs=flat(obs_buf), actions=flat(act_buf),
            logprobs=flat(logp_buf), values=flat(val_buf),
            advantages=flat(adv).astype(np.float32),
            returns=flat(ret).astype(np.float32))
        finished, self._finished_returns = self._finished_returns, []
        return batch, finished

    def ping(self):
        return True


class EnvRunnerGroup:
    """Fault-tolerant group of env-runner actors, built on the shared
    FaultTolerantActorManager (reference: EnvRunnerGroup over
    ``utils/actor_manager.py:198``): a runner dying mid-iteration is
    replaced, re-synced with the last broadcast weights, and re-sampled —
    the iteration keeps its full shard count."""

    def __init__(self, env_creator, module_spec, num_runners: int,
                 num_envs_per_runner: int, gamma: float, lam: float):
        from ray_tpu.rllib.actor_manager import FaultTolerantActorManager

        self._weights = None
        self._weights_version = 0

        def factory(seed: int):
            return ray_tpu.remote(SingleAgentEnvRunner).remote(
                env_creator, module_spec, num_envs_per_runner, seed,
                gamma, lam)

        def on_replace(actor):
            # A fresh replacement starts from version 0 — re-push the
            # last broadcast WITH its version so the respawned runner
            # reports the same weights generation as its peers (the
            # stale-weights re-sync fix), and journal the resync.
            if self._weights is not None:
                from ray_tpu._private import events as _events

                got = ray_tpu.get(
                    actor.set_weights.remote(self._weights,
                                             self._weights_version),
                    timeout=120)
                _events.emit("rl.runner_resync",
                             subject={"group": "env_runners"},
                             version=int(got))

        self._mgr = FaultTolerantActorManager(factory, num_runners,
                                              on_replace=on_replace)

    @property
    def runners(self):
        return self._mgr.actors

    @property
    def weights_version(self) -> int:
        return self._weights_version

    def sync_weights(self, weights, version=None) -> int:
        """Broadcast ``weights`` to every runner, stamped with a version
        (auto-incremented when the caller doesn't supply one). The stored
        (weights, version) pair is what ``on_replace`` re-pushes, so a
        runner respawned mid-iteration can never sample under silently
        stale weights while claiming to be current."""
        from ray_tpu._private import events as _events

        self._weights = weights
        self._weights_version = (int(version) if version is not None
                                 else self._weights_version + 1)
        self._mgr.foreach("set_weights", weights, self._weights_version,
                          timeout_s=120)
        _events.emit("rl.weights_broadcast",
                     subject={"group": "env_runners"},
                     version=self._weights_version,
                     runners=len(self._mgr.actors))
        return self._weights_version

    def sample(self, num_steps: int):
        results = self._mgr.foreach("sample", num_steps)
        batches, episode_returns = [], []
        for _, (batch, finished) in results:
            batches.append(batch)
            episode_returns.extend(finished)
        return batches, episode_returns


class TrajectoryEnvRunner:
    """Decoupled IMPALA-style rollout collector: steps its (stale) behavior
    policy and returns raw [T, N] trajectories with behavior log-probs for
    V-trace correction (reference: the actor half of
    ``rllib/algorithms/impala`` — actors never wait for the learner)."""

    def __init__(self, env_creator: Callable, module_spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0):
        import gymnasium as gym
        import jax

        self.envs = gym.vector.SyncVectorEnv(
            [lambda i=i: env_creator() for i in range(num_envs)])
        self.num_envs = num_envs
        self.module = PPOModule(**module_spec)
        self.params = None
        self.rng = np.random.default_rng(seed)
        self._jax = jax
        self._logp = jax.jit(
            lambda p, o: jax.nn.log_softmax(self.module.logits(p, o)))
        self.obs, _ = self.envs.reset(seed=seed)
        self._episode_returns = np.zeros(num_envs, dtype=np.float64)
        self._finished_returns: List[float] = []

    def set_weights(self, weights, version=None):
        import jax.numpy as jnp

        self.params = self._jax.tree.map(jnp.asarray, weights)
        return _record_weights_version(self, version)

    def get_weights_version(self) -> int:
        return getattr(self, "weights_version", 0)

    def sample(self, num_steps: int):
        T, N = num_steps, self.num_envs
        obs_buf = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        act_buf = np.zeros((T, N), np.int64)
        logp_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        for t in range(T):
            logp_all = np.asarray(
                self._logp(self.params, self.obs.astype(np.float32)))
            probs = np.exp(logp_all)
            probs /= probs.sum(-1, keepdims=True)
            actions = np.array([self.rng.choice(len(p), p=p)
                                for p in probs])
            obs_buf[t] = self.obs
            act_buf[t] = actions
            logp_buf[t] = logp_all[np.arange(N), actions]
            self.obs, rewards, terms, truncs, _ = self.envs.step(actions)
            dones = np.logical_or(terms, truncs)
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._episode_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._finished_returns.append(self._episode_returns[i])
                    self._episode_returns[i] = 0.0
        traj = {
            "obs": obs_buf, "actions": act_buf, "behavior_logp": logp_buf,
            "rewards": rew_buf, "dones": done_buf,
            "bootstrap_obs": self.obs.astype(np.float32),
        }
        finished, self._finished_returns = self._finished_returns, []
        return traj, finished

    def ping(self):
        return True


class _TransitionCollector:
    """Shared transition-collection loop for value-based / off-policy
    runners. Owns the subtle invariants exactly once: gymnasium's
    next-step autoreset (the step after a done is the reset — its action
    is ignored and must not be recorded), termination-vs-truncation
    bootstrapping (time-limit truncations keep their value), and episode
    return tracking. Subclasses supply ``_select(obs) ->
    (env_actions, stored_actions)``."""

    def __init__(self, env_creator: Callable, num_envs: int, seed: int):
        import gymnasium as gym

        self.envs = gym.vector.SyncVectorEnv(
            [lambda i=i: env_creator() for i in range(num_envs)])
        self.num_envs = num_envs
        self.obs, _ = self.envs.reset(seed=seed)
        self._episode_returns = np.zeros(num_envs, dtype=np.float64)
        self._finished_returns: List[float] = []
        self._resetting = np.zeros(num_envs, dtype=bool)

    def _select(self, obs):
        raise NotImplementedError

    def sample(self, num_steps: int):
        from ray_tpu.rllib.core import Transition

        rows = {k: [] for k in
                ("obs", "actions", "rewards", "next_obs", "dones")}
        for _ in range(num_steps):
            env_actions, stored = self._select(self.obs)
            nxt, rewards, terms, truncs, _ = self.envs.step(env_actions)
            valid = ~self._resetting
            rows["obs"].append(self.obs[valid].astype(np.float32))
            rows["actions"].append(stored[valid])
            rows["rewards"].append(rewards[valid].astype(np.float32))
            rows["next_obs"].append(nxt[valid].astype(np.float32))
            # Bootstrapping cuts only at true terminations; time-limit
            # truncations keep their value (partial-episode bootstrap,
            # and `nxt` at the done step is the episode's true final obs).
            rows["dones"].append(terms[valid].astype(np.float32))
            dones = np.logical_or(terms, truncs)
            self._episode_returns[valid] += rewards[valid]
            for i in np.nonzero(dones & valid)[0]:
                self._finished_returns.append(self._episode_returns[i])
                self._episode_returns[i] = 0.0
            self._resetting = dones
            self.obs = nxt
        finished, self._finished_returns = self._finished_returns, []
        return Transition(*[np.concatenate(rows[k]) for k in
                            ("obs", "actions", "rewards", "next_obs",
                             "dones")]), finished

    def ping(self):
        return True


class ContinuousEnvRunner(_TransitionCollector):
    """Transition collector for continuous action spaces (the SAC actor
    side): samples squashed-Gaussian actions from the current policy and
    rescales them into the env's bounds (replay stores the UNIT action —
    the policy's own space)."""

    def __init__(self, env_creator: Callable, module_spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0):
        import jax

        from ray_tpu.rllib.core import SACModule

        super().__init__(env_creator, num_envs, seed)
        self.module = SACModule(**module_spec)
        self.params = None
        space = self.envs.single_action_space
        self._low = np.asarray(space.low, np.float32)
        self._high = np.asarray(space.high, np.float32)
        if not (np.all(np.isfinite(self._low))
                and np.all(np.isfinite(self._high))):
            raise ValueError(
                "ContinuousEnvRunner needs a bounded Box action space "
                f"(got low={space.low}, high={space.high}): the tanh "
                "policy rescales unit actions into [low, high]")
        self._jax = jax
        self._key = jax.random.PRNGKey(seed)
        self._sample_fn = jax.jit(self.module.sample_action)

    def set_weights(self, weights, version=None):
        import jax.numpy as jnp

        self.params = self._jax.tree.map(jnp.asarray, weights)
        return _record_weights_version(self, version)

    def get_weights_version(self) -> int:
        return getattr(self, "weights_version", 0)

    def _select(self, obs):
        self._key, sub = self._jax.random.split(self._key)
        unit, _ = self._sample_fn(self.params, obs.astype(np.float32), sub)
        unit = np.asarray(unit)  # in (-1, 1)
        env_actions = self._low + (unit + 1.0) * 0.5 * (self._high
                                                        - self._low)
        return env_actions, unit


class TransitionEnvRunner(_TransitionCollector):
    """Epsilon-greedy transition collector for value-based algorithms
    (reference: the DQN rollout path of ``single_agent_env_runner.py`` —
    transitions, not GAE trajectories)."""

    def __init__(self, env_creator: Callable, module_spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0):
        import jax

        from ray_tpu.rllib.core import DQNModule

        super().__init__(env_creator, num_envs, seed)
        self.module = DQNModule(**module_spec)
        self.params = None
        self.epsilon = 1.0
        self.rng = np.random.default_rng(seed)
        self._jax = jax
        self._q = jax.jit(self.module.q_values)

    def set_weights(self, weights, version=None):
        import jax.numpy as jnp

        self.params = self._jax.tree.map(jnp.asarray, weights)
        return _record_weights_version(self, version)

    def get_weights_version(self) -> int:
        return getattr(self, "weights_version", 0)

    def set_epsilon(self, epsilon: float):
        self.epsilon = float(epsilon)
        return True

    def _select(self, obs):
        q = np.asarray(self._q(self.params, obs.astype(np.float32)))
        greedy = q.argmax(axis=-1)
        explore = self.rng.random(self.num_envs) < self.epsilon
        random_a = self.rng.integers(0, q.shape[-1], size=self.num_envs)
        actions = np.where(explore, random_a, greedy)
        return actions, actions
