"""IMPALA: decoupled actor/learner RL with V-trace correction.

Reference: ``rllib/algorithms/impala`` — env-runner actors sample with a
stale behavior policy and never block on the learner; the learner consumes
trajectories as they arrive and corrects the off-policyness with V-trace
(``core.vtrace``). This build keeps rollouts in flight continuously: each
``train()`` waits for whichever runner finishes first, updates the
multi-learner :class:`~ray_tpu.rllib.learner_group.LearnerGroup`, pushes
fresh weights to that runner only, and immediately resubmits its next
rollout — the other runners keep sampling under their older policies, which
is exactly the staleness V-trace exists to correct.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import TrajectoryEnvRunner
from ray_tpu.rllib.learner_group import LearnerGroup


@dataclasses.dataclass
class IMPALAConfig:
    env: Optional[str] = None
    env_creator: Optional[Callable] = None
    num_env_runners: int = 2
    num_envs_per_env_runner: int = 4
    rollout_fragment_length: int = 32
    lr: float = 5e-4
    gamma: float = 0.99
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    rho_bar: float = 1.0
    c_bar: float = 1.0
    updates_per_iteration: int = 8
    num_learners: int = 1
    hidden_sizes: tuple = (64, 64)
    seed: int = 0

    # -- fluent builder (reference AlgorithmConfig style) ------------------
    def environment(self, env: Optional[str] = None, *,
                    env_creator: Optional[Callable] = None
                    ) -> "IMPALAConfig":
        self.env = env
        self.env_creator = env_creator
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "IMPALAConfig":
        for k, v in dict(num_env_runners=num_env_runners,
                         num_envs_per_env_runner=num_envs_per_env_runner,
                         rollout_fragment_length=rollout_fragment_length
                         ).items():
            if v is not None:
                setattr(self, k, v)
        return self

    def training(self, **kwargs) -> "IMPALAConfig":
        known = {f.name for f in dataclasses.fields(self)}
        bad = set(kwargs) - known
        if bad:
            raise ValueError(f"Unknown IMPALA training options: "
                             f"{sorted(bad)}")
        for k, v in kwargs.items():
            if v is not None:
                setattr(self, k, v)
        return self

    def learners(self, num_learners: Optional[int] = None) -> "IMPALAConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


def _resolve_env(config) -> Callable:
    if config.env_creator is not None:
        return config.env_creator
    if config.env is None:
        raise ValueError("IMPALAConfig needs .environment(env=...) or "
                         "env_creator")
    import gymnasium as gym

    name = config.env
    return lambda: gym.make(name)


class IMPALA:
    def __init__(self, config: IMPALAConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        creator = _resolve_env(config)
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        probe.close()
        module_spec = {"obs_dim": obs_dim, "num_actions": num_actions,
                       "hidden": tuple(config.hidden_sizes)}
        self._spec = module_spec
        self._creator = creator
        builder = self._learner_builder(module_spec, config)
        self.learner_group = LearnerGroup(builder,
                                          num_learners=config.num_learners)
        runner_cls = ray_tpu.remote(TrajectoryEnvRunner)
        self.runners = [
            runner_cls.remote(creator, module_spec,
                              config.num_envs_per_env_runner, seed)
            for seed in range(config.num_env_runners)
        ]
        weights = self.learner_group.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners],
                    timeout=120)
        # Continuous in-flight rollouts: ref -> runner index.
        self._inflight: Dict[Any, int] = {
            r.sample.remote(config.rollout_fragment_length): i
            for i, r in enumerate(self.runners)}
        self.iteration = 0
        self._returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One iteration = ``updates_per_iteration`` learner updates, each
        on the first trajectory to arrive (actors stay decoupled)."""
        c = self.config
        t0 = time.monotonic()
        metrics: Dict[str, float] = {}
        episodes = 0
        for _ in range(c.updates_per_iteration):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=300)
            if not ready:
                break
            ref = ready[0]
            idx = self._inflight.pop(ref)
            try:
                traj, finished = ray_tpu.get(ref, timeout=60)
            except Exception:  # noqa: BLE001 — runner died: respawn
                runner_cls = ray_tpu.remote(TrajectoryEnvRunner)
                self.runners[idx] = runner_cls.remote(
                    self._creator, self._spec, c.num_envs_per_env_runner,
                    c.seed + 1000 + idx)
                ray_tpu.get(self.runners[idx].set_weights.remote(
                    self.learner_group.get_weights()), timeout=120)
                self._inflight[self.runners[idx].sample.remote(
                    c.rollout_fragment_length)] = idx
                continue
            self._returns.extend(finished)
            episodes += len(finished)
            metrics = self.learner_group.update(traj)
            # Fresh weights to the runner that just delivered; resubmit.
            runner = self.runners[idx]
            runner.set_weights.remote(self.learner_group.get_weights())
            self._inflight[runner.sample.remote(
                c.rollout_fragment_length)] = idx
        self._returns = self._returns[-100:]
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(self._returns))
                                    if self._returns else float("nan")),
            "episodes_this_iter": episodes,
            "time_this_iter_s": time.monotonic() - t0,
            **metrics,
        }

    @staticmethod
    def _learner_builder(module_spec, cfg):
        """Learner factory shipped to the learner actors; subclasses
        (APPO) override to plug a different loss."""
        def builder():
            from ray_tpu.rllib.core import ImpalaLearner, PPOModule

            return ImpalaLearner(PPOModule(**module_spec), lr=cfg.lr,
                                 gamma=cfg.gamma, vf_coeff=cfg.vf_coeff,
                                 entropy_coeff=cfg.entropy_coeff,
                                 rho_bar=cfg.rho_bar, c_bar=cfg.c_bar,
                                 seed=cfg.seed)

        return builder

    def get_weights(self):
        return self.learner_group.get_weights()

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        for a in self.learner_group.learners:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
