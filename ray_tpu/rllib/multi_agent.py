"""Multi-agent RL: env API, rollout runner, and multi-policy PPO.

Reference: ``rllib/env/multi_agent_env.py`` (the dict-keyed env API),
``rllib/env/multi_agent_env_runner.py`` (per-agent episode collection),
and the multi-policy training loop of ``algorithms/ppo`` with
``policy_mapping_fn`` routing agents to policies (``rllib/policy`` /
RLModule spec mapping). Redesigned jax-first: one PPOLearner per policy,
rollouts gathered through the fault-tolerant actor manager
(actor_manager.py) so a dead runner is replaced, re-synced, and re-sampled
within the same iteration.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.core import (PPOLearner, PPOModule, SampleBatch,
                                compute_gae)


class MultiAgentEnv:
    """Dict-keyed multi-agent env (reference: multi_agent_env.py).

    ``reset() -> (obs_dict, info)``; ``step(action_dict) -> (obs_dict,
    reward_dict, terminated_dict, truncated_dict, info)``. The
    ``terminated``/``truncated`` dicts carry the ``"__all__"`` key ending
    the episode for every agent. Agents may appear in any subset of steps;
    only agents present in ``obs_dict`` act next step.
    """

    possible_agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def close(self):
        pass


class _AgentTrajectory:
    """Per-agent rollout buffer: GAE runs over each agent's OWN timeline
    (agents may act on different subsets of env steps)."""

    __slots__ = ("obs", "actions", "logp", "values", "rewards", "dones")

    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.logp: List[float] = []
        self.values: List[float] = []
        self.rewards: List[float] = []
        self.dones: List[float] = []


class MultiAgentEnvRunner:
    """Steps one multi-agent env, routing each agent through its policy's
    module (reference: multi_agent_env_runner.py sample())."""

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 module_specs: Dict[str, Dict[str, Any]],
                 policy_mapping: Callable[[str], str],
                 seed: int = 0, gamma: float = 0.99, lam: float = 0.95):
        import jax

        self.env = env_creator()
        self.gamma = gamma
        self.lam = lam
        self.policy_mapping = policy_mapping
        self.modules = {pid: PPOModule(**spec)
                        for pid, spec in module_specs.items()}
        self.params: Dict[str, Any] = {}
        self.rng = np.random.default_rng(seed)
        self._jax = jax
        self._forwards = {
            pid: jax.jit(lambda p, o, m=m: (
                jax.nn.log_softmax(m.logits(p, o)), m.value(p, o)))
            for pid, m in self.modules.items()}
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0

    def set_weights(self, weights_by_policy: Dict[str, Any]) -> bool:
        import jax.numpy as jnp

        self.params = {pid: self._jax.tree.map(jnp.asarray, w)
                       for pid, w in weights_by_policy.items()}
        return True

    def _act(self, agent_id: str, obs) -> Tuple[int, float, float]:
        pid = self.policy_mapping(agent_id)
        logp_all, value = self._forwards[pid](
            self.params[pid], np.asarray(obs, np.float32)[None])
        logp_all = np.asarray(logp_all)[0]
        probs = np.exp(logp_all)
        probs /= probs.sum()
        action = int(self.rng.choice(len(probs), p=probs))
        return action, float(logp_all[action]), float(np.asarray(value)[0])

    def sample(self, num_steps: int):
        """Collect ``num_steps`` env steps. Returns
        ``(per_policy_batches, episode_returns)`` where each batch is a
        dict of SampleBatch fields."""
        trajs: Dict[str, _AgentTrajectory] = {}
        finished: Dict[str, List[_AgentTrajectory]] = {}
        episode_returns: List[float] = []

        def finish_episode():
            for aid, traj in trajs.items():
                if traj.dones:
                    traj.dones[-1] = 1.0
                finished.setdefault(aid, []).append(traj)
            trajs.clear()

        for _ in range(num_steps):
            actions: Dict[str, Any] = {}
            for aid, obs in self._obs.items():
                a, logp, v = self._act(aid, obs)
                actions[aid] = a
                traj = trajs.setdefault(aid, _AgentTrajectory())
                traj.obs.append(np.asarray(obs, np.float32))
                traj.actions.append(a)
                traj.logp.append(logp)
                traj.values.append(v)
            obs, rewards, terms, truncs, _ = self.env.step(actions)
            for aid in actions:
                traj = trajs[aid]
                traj.rewards.append(float(rewards.get(aid, 0.0)))
                done = bool(terms.get(aid) or truncs.get(aid))
                traj.dones.append(1.0 if done else 0.0)
                self._episode_return += float(rewards.get(aid, 0.0))
            if terms.get("__all__") or truncs.get("__all__"):
                finish_episode()
                episode_returns.append(self._episode_return)
                self._episode_return = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = obs
        # Rollout ended mid-episode: bootstrap each live trajectory with
        # the agent's current value estimate.
        bootstraps: Dict[str, float] = {}
        for aid, obs in self._obs.items():
            if aid in trajs:
                _, _, v = self._act(aid, obs)
                bootstraps[aid] = v
        for aid, traj in trajs.items():
            finished.setdefault(aid, []).append(traj)
        per_policy: Dict[str, Dict[str, np.ndarray]] = {}
        for aid, traj_list in finished.items():
            pid = self.policy_mapping(aid)
            for traj in traj_list:
                if not traj.rewards:
                    continue
                T = len(traj.rewards)
                rew = np.asarray(traj.rewards, np.float32).reshape(T, 1)
                val = np.asarray(traj.values, np.float32).reshape(T, 1)
                don = np.asarray(traj.dones, np.float32).reshape(T, 1)
                last_v = np.asarray(
                    [0.0 if don[-1, 0] else bootstraps.get(aid, 0.0)],
                    np.float32)
                adv, ret = compute_gae(rew, val, don, last_v, self.gamma,
                                       self.lam)
                out = per_policy.setdefault(pid, {
                    f: [] for f in SampleBatch._fields})
                out["obs"].append(np.stack(traj.obs))
                out["actions"].append(np.asarray(traj.actions, np.int64))
                out["logprobs"].append(np.asarray(traj.logp, np.float32))
                out["values"].append(val[:, 0])
                out["advantages"].append(adv[:, 0].astype(np.float32))
                out["returns"].append(ret[:, 0].astype(np.float32))
        batches = {
            pid: {f: np.concatenate(v) for f, v in fields.items()}
            for pid, fields in per_policy.items()}
        return batches, episode_returns

    def ping(self):
        return True


@dataclasses.dataclass
class MultiAgentPPOConfig:
    env_creator: Optional[Callable] = None
    policies: Optional[Dict[str, Dict[str, Any]]] = None  # pid->module_spec
    policy_mapping_fn: Optional[Callable[[str], str]] = None
    num_env_runners: int = 2
    rollout_fragment_length: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    entropy_coeff: float = 0.0
    num_epochs: int = 4
    minibatch_size: int = 128
    seed: int = 0

    def environment(self, *, env_creator: Callable) -> "MultiAgentPPOConfig":
        self.env_creator = env_creator
        return self

    def multi_agent(self, *, policies: Dict[str, Dict[str, Any]],
                    policy_mapping_fn: Callable[[str], str]
                    ) -> "MultiAgentPPOConfig":
        self.policies = policies
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def env_runners(self, num_env_runners: int) -> "MultiAgentPPOConfig":
        self.num_env_runners = num_env_runners
        return self

    def training(self, **kw) -> "MultiAgentPPOConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise AttributeError(k)
            setattr(self, k, v)
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """Multi-policy PPO: one jitted learner per policy, shared rollouts."""

    def __init__(self, config: MultiAgentPPOConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if not config.policies or config.policy_mapping_fn is None:
            raise ValueError("multi_agent(policies=..., policy_mapping_fn"
                             "=...) is required")
        self.config = config
        self.learners = {
            pid: PPOLearner(
                PPOModule(**spec), lr=config.lr, clip=config.clip_param,
                entropy_coeff=config.entropy_coeff,
                num_epochs=config.num_epochs,
                minibatch_size=config.minibatch_size,
                seed=config.seed + i)
            for i, (pid, spec) in enumerate(config.policies.items())}
        creator = config.env_creator
        specs = config.policies
        mapping = config.policy_mapping_fn

        def factory(index: int):
            return ray_tpu.remote(MultiAgentEnvRunner).remote(
                creator, specs, mapping, config.seed + index,
                config.gamma, config.lambda_)

        self._last_weights = self.get_weights()
        self.runners = FaultTolerantActorManager(
            factory, config.num_env_runners,
            on_replace=lambda a: ray_tpu.get(
                a.set_weights.remote(self._last_weights), timeout=120))
        self.iteration = 0
        self._recent_returns: List[float] = []

    def get_weights(self) -> Dict[str, Any]:
        return {pid: ln.get_weights() for pid, ln in self.learners.items()}

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        self._last_weights = self.get_weights()
        self.runners.foreach("set_weights", self._last_weights,
                             timeout_s=120)
        results = self.runners.foreach(
            "sample", self.config.rollout_fragment_length)
        merged: Dict[str, Dict[str, List[np.ndarray]]] = {}
        episode_returns: List[float] = []
        for _, (batches, returns) in results:
            episode_returns.extend(returns)
            for pid, fields in batches.items():
                out = merged.setdefault(
                    pid, {f: [] for f in SampleBatch._fields})
                for f, arr in fields.items():
                    out[f].append(arr)
        metrics: Dict[str, Any] = {}
        steps = 0
        for pid, fields in merged.items():
            batch = SampleBatch(**{
                f: np.concatenate(v) for f, v in fields.items()})
            steps += len(batch.obs)
            for k, v in self.learners[pid].update_from_batch(batch).items():
                metrics[f"learner/{pid}/{k}"] = v
        self.iteration += 1
        self._recent_returns.extend(episode_returns)
        self._recent_returns = self._recent_returns[-100:]
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan")),
            "num_env_steps_sampled": steps,
            "env_steps_per_sec": steps / (time.perf_counter() - t0),
            "num_runner_replacements": self.runners.num_replacements,
            **metrics,
        }

    def stop(self):
        for r in self.runners.actors:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass


__all__ = ["MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
           "MultiAgentPPOConfig"]
