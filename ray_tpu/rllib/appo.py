"""APPO: asynchronous PPO on the IMPALA machinery.

Reference: ``rllib/algorithms/appo/appo.py`` — APPO is IMPALA's decoupled
actor/learner architecture (stale behavior policies, V-trace off-policy
correction, continuous in-flight rollouts) with PPO's clipped surrogate
objective in place of the plain V-trace policy gradient: the likelihood
ratio is taken against the BEHAVIOR policy (the async analog of PPO's
"old" policy) and clipped to ``clip_param``, bounding per-update policy
movement while sampling never blocks on learning.

Everything else — env runners, the multi-learner
:class:`~ray_tpu.rllib.learner_group.LearnerGroup` allreduce, runner
respawn on failure — is inherited from :class:`~ray_tpu.rllib.impala.IMPALA`.
"""

from __future__ import annotations

import dataclasses

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig


@dataclasses.dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float = 0.2

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    @staticmethod
    def _learner_builder(module_spec, cfg):
        def builder():
            from ray_tpu.rllib.core import ImpalaLearner, PPOModule

            return ImpalaLearner(PPOModule(**module_spec), lr=cfg.lr,
                                 gamma=cfg.gamma, vf_coeff=cfg.vf_coeff,
                                 entropy_coeff=cfg.entropy_coeff,
                                 rho_bar=cfg.rho_bar, c_bar=cfg.c_bar,
                                 seed=cfg.seed,
                                 clip_param=cfg.clip_param)

        return builder


__all__ = ["APPO", "APPOConfig"]
