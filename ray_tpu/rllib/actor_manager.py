"""Fault-tolerant actor manager for RLlib worker fleets.

Reference: ``rllib/utils/actor_manager.py:198`` (FaultTolerantActorManager)
— async ``foreach`` over a fleet of actors where failures mark the actor
unhealthy, the fleet restarts it, and (optionally) the failed call is
retried on the replacement so an iteration keeps its full shard count
instead of silently shrinking.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class FaultTolerantActorManager:
    """Owns a fleet of same-class actors created by ``factory(index)``.

    ``foreach`` fans a method call across the fleet and gathers results;
    an actor whose call fails is replaced via the factory, and with
    ``retry_on_replacement`` the call re-runs on the replacement (after
    ``on_replace`` re-initializes it, e.g. re-syncing weights) so the
    caller still receives one result per slot.
    """

    def __init__(self, factory: Callable[[int], Any], num_actors: int,
                 on_replace: Optional[Callable[[Any], None]] = None):
        self._factory = factory
        self._on_replace = on_replace
        self._next_index = num_actors
        self.actors: List[Any] = [factory(i) for i in range(num_actors)]
        self.num_replacements = 0

    def __len__(self) -> int:
        return len(self.actors)

    def _replace(self, slot: int):
        old = self.actors[slot]
        try:
            # This runtime has no handle-refcount actor GC: dropping the
            # handle would leak a possibly-still-running actor process.
            ray_tpu.kill(old)
        except Exception:  # noqa: BLE001
            pass
        self._next_index += 1
        self.num_replacements += 1
        actor = self._factory(self._next_index)
        self.actors[slot] = actor
        if self._on_replace is not None:
            try:
                self._on_replace(actor)
            except Exception:  # noqa: BLE001
                logger.exception("on_replace failed for slot %d", slot)
        return actor

    def foreach(self, method: str, *args, timeout_s: float = 300.0,
                retry_on_replacement: bool = True,
                **kwargs) -> List[Tuple[int, Any]]:
        """Call ``method(*args, **kwargs)`` on every actor concurrently.

        Returns ``[(slot, result), ...]`` for every slot that produced a
        result. A failed call replaces the actor; with retry the call
        re-runs ONCE on the replacement (a second failure drops the slot
        from this round — deterministic failures must not loop forever).
        """
        refs = [(slot, getattr(a, method).remote(*args, **kwargs))
                for slot, a in enumerate(self.actors)]
        results: List[Tuple[int, Any]] = []
        retry: List[int] = []
        for slot, ref in refs:
            try:
                results.append((slot, ray_tpu.get(ref, timeout=timeout_s)))
            except Exception as e:  # noqa: BLE001
                logger.warning("actor slot %d failed %s: %s; replacing",
                               slot, method, e)
                self._replace(slot)
                if retry_on_replacement:
                    retry.append(slot)
        for slot in retry:
            try:
                ref = getattr(self.actors[slot], method).remote(*args,
                                                                **kwargs)
                results.append((slot, ray_tpu.get(ref, timeout=timeout_s)))
            except Exception as e:  # noqa: BLE001
                logger.warning("replacement for slot %d also failed %s: %s",
                               slot, method, e)
                self._replace(slot)
        results.sort(key=lambda t: t[0])
        return results

    def healthy_count(self, timeout_s: float = 10.0) -> int:
        """Count responsive actors. A ping TIMEOUT counts as healthy-but
        -busy (these actors are serial: a ping queues behind a long
        sample(), and replacing a busy actor would discard its work);
        only a dead actor is replaced."""
        from ray_tpu.exceptions import GetTimeoutError

        alive = 0
        probes = [(slot, a.ping.remote()) for slot, a in
                  enumerate(self.actors)]
        for slot, ref in probes:
            try:
                ray_tpu.get(ref, timeout=timeout_s)
                alive += 1
            except GetTimeoutError:
                alive += 1  # busy, not dead
            except Exception:  # noqa: BLE001
                self._replace(slot)
        return alive


__all__ = ["FaultTolerantActorManager"]
