"""Public exception types of the ray_tpu framework.

Re-design of the reference error model (reference: ``python/ray/exceptions.py``,
``src/ray/common/status.h``): errors raised inside a remote task are captured,
stored as the task's return object, and re-raised at ``ray_tpu.get`` on the
caller, wrapped so the remote traceback is preserved.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayTpuError):
    """Wraps an exception raised inside a remote task/actor method.

    Stored as the value of the task's return object; re-raised on ``get``.
    The remote traceback string is carried so the user sees the real failure
    site (reference: ``python/ray/exceptions.py::RayTaskError``).
    """

    def __init__(
        self,
        function_name: str = "",
        traceback_str: str = "",
        cause: Optional[BaseException] = None,
        task_id=None,
    ):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        self.task_id = task_id
        super().__init__(function_name, traceback_str)

    @classmethod
    def from_exception(cls, exc: BaseException, function_name: str, task_id=None):
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        return cls(function_name=function_name, traceback_str=tb, cause=exc, task_id=task_id)

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is-a instance of the original cause's class.

        Allows ``except ValueError`` on the caller to catch a remote ValueError.
        """
        cause = self.cause
        if cause is None:
            return self
        if isinstance(cause, RayTaskError):
            return cause.as_instanceof_cause()

        cause_cls = type(cause)
        if issubclass(cause_cls, RayTpuError):
            return cause
        try:

            class _cls(RayTaskError, cause_cls):  # type: ignore[misc, valid-type]
                def __init__(self, inner: RayTaskError):
                    self._inner = inner

                def __getattr__(self, name):
                    return getattr(self._inner, name)

                def __str__(self):
                    return str(self._inner)

            _cls.__name__ = f"RayTaskError({cause_cls.__name__})"
            _cls.__qualname__ = _cls.__name__
            return _cls(self)
        except TypeError:
            return self

    def __str__(self):
        return (
            f"{type(self).__name__}: task {self.function_name!r} failed\n"
            f"{self.traceback_str}"
        )


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(task_id)


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` did not complete within the requested timeout."""


class ActorDiedError(RayTaskError):
    """The actor died before or while executing the task (reference:
    ``python/ray/exceptions.py::RayActorError``)."""

    def __init__(self, actor_id=None, error_msg: str = "The actor died unexpectedly."):
        self.actor_id = actor_id
        self.error_msg = error_msg
        self.function_name = ""
        self.traceback_str = error_msg
        self.cause = None
        self.task_id = None
        RayTpuError.__init__(self, error_msg)

    def __str__(self):
        return self.error_msg


# Compatibility alias matching the reference public name.
RayActorError = ActorDiedError


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """The object's value was lost and could not be reconstructed."""

    def __init__(self, object_ref=None, message: str = ""):
        self.object_ref = object_ref
        super().__init__(message or f"Object {object_ref} was lost.")


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage reconstruction for a lost object failed (e.g. retries exhausted)."""


class OwnerDiedError(ObjectLostError):
    """The worker that owned this object died, taking its metadata with it."""


class ObjectStoreFullError(RayTpuError):
    """The local shared-memory object store is out of memory."""


class OutOfMemoryError(RayTpuError):
    """A worker was killed by the memory monitor to avoid node OOM."""


class RuntimeEnvSetupError(RayTpuError):
    """Creating the runtime environment for a task/actor failed."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class PreemptedError(RayTpuError):
    """A training worker stopped because its host is being preempted
    (SIGTERM / TPU maintenance event). Raised by train loops after their
    just-in-time checkpoint; the trainer controller treats it as
    retryable and resumes from the newest committed manifest."""

    def __init__(self, reason: str = "host preempted", notice=None):
        self.reason = reason
        self.notice = notice
        super().__init__(reason)


class NodeDiedError(RayTpuError):
    """The node running the task/actor died."""


class WorkerHangError(RayTpuError):
    """A training worker stopped making progress while staying reachable:
    either its per-step watchdog lapsed (hung collective / wedged step)
    or its heartbeats stopped arriving. Retryable — the elastic trainer
    tears the group down and re-forms it (restart budget, not
    ``max_failures``)."""

    def __init__(self, reason: str = "worker hang detected",
                 rank=None, kind: str = "watchdog"):
        self.reason = reason
        self.rank = rank
        self.kind = kind  # "watchdog" | "heartbeat"
        super().__init__(reason)


class WorkerStoppedError(RayTpuError):
    """Cooperative stop: the controller is tearing this worker group down
    (elastic restart/resize) and the session's stop flag is set. Raised
    out of ``train.report()`` so in-process zombie loops unwind instead
    of racing the next attempt's checkpoint writes."""


class NaNLossError(RayTpuError):
    """The training loss was non-finite for too many consecutive reports.
    Classified FATAL: restarting from the same checkpoint would replay
    the same divergence, so no retry budget is consumed."""

    def __init__(self, reason: str = "non-finite training loss",
                 reports: int = 0):
        self.reports = reports
        super().__init__(f"{reason} ({reports} consecutive reports)")


class JaxDistributedBootstrapError(RayTpuError):
    """Forming the multi-process ``jax.distributed`` group failed after
    coordinator port-rebind retries — the environment cannot run
    multi-process jax (fatal, not retryable)."""


class CheckpointCorruptError(RayTpuError):
    """A committed checkpoint's shard data failed integrity verification
    (crc32 mismatch against the spec, unreadable/truncated shard file).
    ``CheckpointPlane.restore``/``load_latest`` fall back to the previous
    committed manifest instead of surfacing this."""


class ReplicaDrainingError(RayTpuError):
    """The serve replica is draining (controller-initiated: scale-down,
    preemption, rolling update) and no longer admits new requests. A
    clean reject — the replica did no work — so routers retry on another
    replica without consuming the request's resume budget."""

    def __init__(self, reason: str = "replica is draining"):
        self.reason = reason
        super().__init__(reason)


class ResumeExhaustedError(RayTpuError):
    """A serve request's per-request resume budget
    (``RAY_TPU_SERVE_MAX_RESUMES``) ran out: the request was resubmitted/
    resumed after replica death the maximum number of times and the last
    attempt also failed. Terminal — the caller sees this instead of the
    raw ``ActorDiedError`` so it can distinguish "the fabric tried and
    gave up" from "a replica died"."""

    def __init__(self, reason: str = "resume budget exhausted",
                 resumes: int = 0):
        self.resumes = resumes
        super().__init__(f"{reason} (after {resumes} resume(s))")


class RaySystemError(RayTpuError):
    """Internal framework failure (deserialization, protocol, ...)."""


class PendingCallsLimitExceeded(RayTpuError):
    """An actor's pending call queue exceeded ``max_pending_calls``."""


class AsyncioActorExit(RayTpuError):
    """Internal: signals an async actor to exit."""
