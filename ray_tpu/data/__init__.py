"""ray_tpu.data: distributed data processing (reference: ``python/ray/data``)."""

from ray_tpu.data.datasource import (
    BinaryFilesDatasource,
    CSVDatasource,
    Datasource,
    JSONDatasource,
    ParquetDatasource,
    TextDatasource,
    read_datasource,
)
from ray_tpu.data.dataset import (
    ActorPoolStrategy,
    DataIterator,
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)

__all__ = [
    "Datasource", "read_datasource",
    "BinaryFilesDatasource", "CSVDatasource", "JSONDatasource",
    "ParquetDatasource", "TextDatasource",
    "ActorPoolStrategy", "DataIterator", "Dataset", "from_arrow", "from_items", "from_numpy",
    "from_pandas", "range", "read_binary_files", "read_csv", "read_json",
    "read_parquet", "read_text",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("data")
del _rlu
