"""ray_tpu.data: distributed data processing (reference: ``python/ray/data``)."""

from ray_tpu.data.dataset import (
    DataIterator,
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_csv,
    read_json,
    read_parquet,
)

__all__ = [
    "DataIterator", "Dataset", "from_arrow", "from_items", "from_numpy",
    "from_pandas", "range", "read_csv", "read_json", "read_parquet",
]
