"""ray_tpu.data: distributed datasets with a lazy plan + streaming execution.

Reference: ``python/ray/data`` (SURVEY.md §2.4) — a ``Dataset`` is a lazy
logical plan over blocks (pyarrow Tables, ``data/block.py``), compiled to
tasks by a streaming executor with bounded in-flight work
(``_internal/execution/streaming_executor.py:48``). This build keeps that
shape: blocks are ``ObjectRef``s of pyarrow Tables, per-block transforms run
as remote tasks with a bounded window (backpressure), and all-to-all ops
(shuffle/sort/repartition/groupby) materialize their stage.

The training-ingest path (``streaming_split``, ``iter_batches``) feeds
jax/numpy batches; ``batch_format="numpy"`` returns dict-of-ndarrays ready
for ``jax.device_put`` onto a mesh.
"""

from __future__ import annotations

import builtins
import glob as glob_mod
import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np
import pyarrow as pa

import ray_tpu

Batch = Union[Dict[str, np.ndarray], "pa.Table", "pandas.DataFrame"]

MAX_IN_FLIGHT = 16  # streaming window hard cap (backpressure bound)
_STATS_ACTOR = "_rtpu_data_stats"


_stats_handle = None
_stats_handle_core = None
_stats_lock = threading.Lock()


def reset_stats_cache() -> None:
    """Drop the cached stats-actor handle. Process-global state: tests
    that run many init/shutdown cycles in ONE process (the tier-1 suite
    is single-process) call this between sessions so a handle minted
    against a previous runtime can never eat the first records of the
    next one (the in-suite-only stats flake)."""
    global _stats_handle, _stats_handle_core
    with _stats_lock:
        _stats_handle = None
        _stats_handle_core = None


def _record_stats(stats_key, op: str, rows_in: int, rows_out: int,
                  seconds: float) -> None:
    """Fire-and-forget per-block stats to the session stats actor
    (reference: ``_StatsActor``, ``data/_internal/stats.py``). The handle
    is cached per runtime — a per-block name lookup would add a GCS
    round-trip to the very latency being measured, and a handle cached
    across init/shutdown cycles would silently drop records. The cache
    is lock-guarded: concurrent block tasks in the in-process runtime
    share these module globals, and an unguarded miss/reset race could
    publish a handle paired with the WRONG core (records then land in a
    dead session's actor until the next exception resets it)."""
    global _stats_handle, _stats_handle_core
    if not stats_key:
        return
    try:
        from ray_tpu._private import worker as _worker_mod

        core = _worker_mod.global_worker().core
        with _stats_lock:
            if _stats_handle is None or _stats_handle_core is not core:
                _stats_handle = ray_tpu.get_actor(_STATS_ACTOR)
                _stats_handle_core = core
            handle = _stats_handle
        handle.record.remote(stats_key, op, rows_in, rows_out, seconds)
    except Exception:  # noqa: BLE001 — stats are best-effort
        reset_stats_cache()


class _StatsActor:
    """Session-wide collector of per-operator execution stats. Bounded:
    only the most recent executions are retained (long-lived sessions
    re-executing datasets every epoch would otherwise grow it forever)."""

    MAX_KEYS = 256

    def __init__(self):
        self.data: Dict[str, Dict[str, list]] = {}

    def record(self, key, op, rows_in, rows_out, seconds):
        if key not in self.data:
            while len(self.data) >= self.MAX_KEYS:
                self.data.pop(next(iter(self.data)))
        entry = self.data.setdefault(key, {}).setdefault(
            op, [0, 0, 0.0, 0])  # rows_in, rows_out, seconds, blocks
        entry[0] += rows_in
        entry[1] += rows_out
        entry[2] += seconds
        entry[3] += 1

    def get(self, key):
        return self.data.get(key, {})


# ----------------------------------------------------------------- block ops
def _table_from_rows(rows: List[Any]) -> pa.Table:
    if rows and not isinstance(rows[0], dict):
        rows = [{"item": r} for r in rows]
    if not rows:
        return pa.table({})
    arrays, fields = [], []
    for k in rows[0]:
        vals = [r.get(k) for r in rows]
        first = vals[0]
        if (isinstance(first, np.ndarray) and first.ndim >= 1
                and all(isinstance(v, np.ndarray)
                        and v.shape == first.shape
                        and v.dtype == first.dtype for v in vals)):
            # Rectangular per-row ndarrays (LM tokens, images) become a
            # TENSOR column: a bare pa.array would store variable-length
            # lists, and batch_format="numpy" would then hand back
            # object-dtype arrays that jax.device_put rejects — the
            # train-ingest path needs the exact [B, ...] ndarray back.
            col, meta = _tensor_column(np.stack(vals))
            fields.append(pa.field(k, col.type, metadata=meta))
        else:
            col = pa.array(vals)
            fields.append(pa.field(k, col.type))
        arrays.append(col)
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _tensor_fields(table: pa.Table):
    return [(i, table.schema.field(i)) for i in
            builtins.range(table.num_columns)
            if pa.types.is_fixed_size_list(table.schema.field(i).type)
            and table.schema.field(i).metadata
            and b"tensor_shape" in table.schema.field(i).metadata]


def _rows_of(table: pa.Table) -> List[Dict[str, Any]]:
    rows = table.to_pylist()
    # Tensor columns come back as per-row ndarrays with their true shape
    # (to_pylist alone would hand out the flattened fixed-size list).
    for i, field in _tensor_fields(table):
        arrs = _tensor_column_to_numpy(table.column(i), field)
        for row, a in zip(rows, arrs):
            row[field.name] = a
    return rows


def _tensor_column_to_numpy(col: pa.ChunkedArray, field: pa.Field):
    """Reassemble a tensor column ([N, d1, d2, ...] ndarray stored as a
    FixedSizeList of the flattened trailing dims) without a per-row copy:
    the flat value buffer views straight into an ndarray and reshapes."""
    import json as _json

    arr = col.combine_chunks()
    flat = arr.values.to_numpy(zero_copy_only=False)
    shape = None
    if field.metadata and b"tensor_shape" in field.metadata:
        shape = tuple(_json.loads(field.metadata[b"tensor_shape"]))
    if shape is None:
        shape = (arr.type.list_size,)
    return flat.reshape((len(arr),) + shape)


def _batch_of(table: pa.Table, fmt: str):
    if fmt == "pyarrow":
        return table
    if fmt == "pandas":
        df = table.to_pandas()
        for i, field in _tensor_fields(table):
            # Per-cell ndarrays with the true tensor shape, not the
            # flattened fixed-size list.
            df[field.name] = list(_tensor_column_to_numpy(table.column(i),
                                                          field))
        return df
    out = {}
    for i, name in enumerate(table.column_names):
        field = table.schema.field(i)
        if pa.types.is_fixed_size_list(field.type):
            out[name] = _tensor_column_to_numpy(table.column(i), field)
        else:
            out[name] = table.column(i).to_numpy(zero_copy_only=False)
    return out


def _tensor_column(arr: np.ndarray):
    """(array, field_metadata) for a rectangular [N, d1, d2, ...] tensor:
    stored as a FixedSizeList over the flattened trailing dims, shape in
    the field metadata — iter_batches reconstructs the exact ndarray with
    no per-row copies, ready to shard onto a device mesh."""
    import json as _json

    n = arr.shape[0]
    inner = int(np.prod(arr.shape[1:])) if arr.ndim > 1 else 1
    flat = pa.array(np.ascontiguousarray(arr).reshape(-1))
    values = pa.FixedSizeListArray.from_arrays(flat, inner)
    meta = {b"tensor_shape": _json.dumps(list(arr.shape[1:])).encode()}
    return values, meta


def _table_from_batch(batch) -> pa.Table:
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        names, arrays, fields = [], [], []
        for k, v in batch.items():
            if isinstance(v, np.ndarray):
                arr = v
            else:
                try:
                    arr = np.asarray(v)
                except Exception:  # noqa: BLE001 — truly ragged input
                    arr = np.asarray(v, dtype=object)
            if arr.ndim > 1 and arr.dtype != object:
                # Rectangular tensor column (embeddings, images, token
                # blocks): fixed-size-list layout, shape in metadata.
                values, meta = _tensor_column(arr)
                col = values
                field = pa.field(k, values.type, metadata=meta)
            elif arr.dtype == object:
                # Ragged / nested rows (variable-length token lists):
                # build an Arrow list array instead of a flat one.
                col = pa.array([
                    None if x is None
                    else (list(x) if hasattr(x, "__len__")
                          and not isinstance(x, (str, bytes, dict))
                          else x)
                    for x in v])
                field = pa.field(k, col.type)
            else:
                col = pa.array(arr)
                field = pa.field(k, col.type)
            names.append(k)
            arrays.append(col)
            fields.append(field)
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    import pandas as pd

    if isinstance(batch, pd.DataFrame):
        return pa.Table.from_pandas(batch, preserve_index=False)
    raise TypeError(f"unsupported batch type {type(batch)}")


# remote per-block kernels (module-level so they pickle by reference)
@ray_tpu.remote
def _map_block(table: pa.Table, fn, stats_key=None) -> pa.Table:
    import time as _time

    t0 = _time.perf_counter()
    out = _table_from_rows([fn(r) for r in _rows_of(table)])
    _record_stats(stats_key, "map", len(table), len(out),
                  _time.perf_counter() - t0)
    return out


@ray_tpu.remote
def _map_batches_block(table: pa.Table, fn, fmt: str,
                       stats_key=None) -> pa.Table:
    import time as _time

    t0 = _time.perf_counter()
    out = _table_from_batch(fn(_batch_of(table, fmt)))
    _record_stats(stats_key, "map_batches", len(table), len(out),
                  _time.perf_counter() - t0)
    return out


@ray_tpu.remote
def _filter_block(table: pa.Table, fn, stats_key=None) -> pa.Table:
    import time as _time

    t0 = _time.perf_counter()
    out = _table_from_rows([r for r in _rows_of(table) if fn(r)])
    _record_stats(stats_key, "filter", len(table), len(out),
                  _time.perf_counter() - t0)
    return out


@ray_tpu.remote
def _flat_map_block(table: pa.Table, fn, stats_key=None) -> pa.Table:
    import time as _time

    t0 = _time.perf_counter()
    out: List[Any] = []
    for r in _rows_of(table):
        out.extend(fn(r))
    out = _table_from_rows(out)
    _record_stats(stats_key, "flat_map", len(table), len(out),
                  _time.perf_counter() - t0)
    return out


class _MapWorker:
    """Actor hosting a stateful map_batches callable (reference:
    ``ActorPoolMapOperator`` — a class UDF is constructed ONCE per pool
    actor and reused for every batch, amortizing model loads)."""

    def __init__(self, fn_or_cls, ctor_args, ctor_kwargs):
        if isinstance(fn_or_cls, type):
            self.fn = fn_or_cls(*ctor_args, **(ctor_kwargs or {}))
        else:
            self.fn = fn_or_cls

    def map_batch(self, table: pa.Table, fmt: str,
                  stats_key=None) -> pa.Table:
        import time as _time

        t0 = _time.perf_counter()
        out = _table_from_batch(self.fn(_batch_of(table, fmt)))
        _record_stats(stats_key, "map_batches(actors)", len(table),
                      len(out), _time.perf_counter() - t0)
        return out

    def ping(self):
        return True



@ray_tpu.remote
def _block_len(table: pa.Table) -> int:
    return len(table)


@ray_tpu.remote
def _slice_block(table: pa.Table, off: int, length: int) -> pa.Table:
    return table.slice(off, length)


@ray_tpu.remote
def _zip_block(left: pa.Table, *right_parts) -> pa.Table:
    right = (pa.concat_tables([p for p in right_parts if len(p)])
             if any(len(p) for p in right_parts) else pa.table({}))
    # Rebuild with the SOURCE fields (not bare pa.table) so tensor-column
    # shape metadata survives the zip.
    arrays, fields, seen = [], [], set()
    for i, name in enumerate(left.column_names):
        arrays.append(left.column(i))
        fields.append(left.schema.field(i))
        seen.add(name)
    for i, name in enumerate(right.column_names):
        out_name = name
        while out_name in seen:  # reference: right-side dups get _1
            out_name += "_1"
        seen.add(out_name)
        arrays.append(right.column(i))
        f = right.schema.field(i)
        fields.append(pa.field(out_name, f.type, metadata=f.metadata))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _key_partition(v, n: int) -> int:
    """Partition index for a join key. Must respect EQUALITY (0.0 == -0.0
    == 0 must land together — repr-hashing broke that) and be stable
    ACROSS PROCESSES (str hash() is seed-randomized, so strings go
    through crc32; numeric hash() is deterministic)."""
    import zlib

    if isinstance(v, bytes):
        return zlib.crc32(v) % n
    if isinstance(v, str):
        return zlib.crc32(v.encode()) % n
    if isinstance(v, (int, float, np.integer, np.floating)):
        if v != v:
            return 0  # NaN: hash() is id-based on 3.10+, but Arrow's
            # join matches NaN==NaN — give every NaN one bucket.
        return hash(v) % n  # Python numeric hash: equal values, equal hash
    return zlib.crc32(repr(v).encode()) % n


@ray_tpu.remote
def _hash_partition_block(table: pa.Table, key: str, n: int):
    """Split one block into n key-hashed parts (join map stage)."""
    if key not in table.column_names:
        if table.num_columns:
            raise KeyError(
                f"join key {key!r} not in columns {table.column_names}")
        col = []  # genuinely schema-less empty block
    else:
        col = table.column(key).to_pylist()
    idx = [[] for _ in builtins.range(n)]
    for i, v in enumerate(col):
        idx[_key_partition(v, n)].append(i)
    parts = [table.take(pa.array(ix, type=pa.int64()))
             for ix in idx]
    return tuple(parts) if n > 1 else parts[0]


@ray_tpu.remote
def _join_reduce(join_type: str, on, n_left: int, *parts) -> pa.Table:
    # Keep empty partitions: they carry the side's full SCHEMA, which
    # the outer join variants need to null-fill missing columns.
    lparts = [p for p in parts[:n_left] if p.num_columns]
    rparts = [p for p in parts[n_left:] if p.num_columns]
    if not lparts or not rparts:
        return pa.table({})  # a schema-less side: nothing to join
    lt = pa.concat_tables(lparts)
    rt = pa.concat_tables(rparts)
    if not len(lt) and join_type in ("inner", "left outer"):
        return pa.table({})
    if not len(rt) and join_type in ("inner", "right outer"):
        return pa.table({})
    out = lt.join(rt, keys=on, join_type=join_type)
    # Arrow's join drops field metadata: re-attach tensor shapes from
    # whichever source schema carries the same-named field.
    fields = []
    changed = False
    for i, name in enumerate(out.column_names):
        f = out.schema.field(i)
        for src in (lt, rt):
            if name in src.schema.names:
                sf = src.schema.field(name)
                if sf.metadata:
                    f = f.with_metadata(sf.metadata)
                    changed = True
                break
        fields.append(f)
    if changed:
        out = out.cast(pa.schema(fields))
    return out


class ActorPoolStrategy:
    """Fixed-size actor pool for stateful map_batches (reference:
    ``ray.data.ActorPoolStrategy`` — min/max autoscaling pool, fixed here)."""

    def __init__(self, size: int = 2):
        self.size = max(int(size), 1)


class Dataset:
    """Lazy plan: a list of block-producing thunks + pending transforms."""

    def __init__(self, block_refs: List[Any], plan: Optional[List] = None):
        self._block_refs = block_refs  # ObjectRefs of pa.Table
        self._plan = plan or []       # [(op, payload), ...] pending stages
        self._last_stats_key: Optional[str] = None

    # -------------------------------------------------------------- plan ops
    def _with(self, op: str, payload) -> "Dataset":
        return Dataset(self._block_refs, self._plan + [(op, payload)])

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._with("map", fn)

    def map_batches(self, fn: Union[Callable[[Batch], Batch], type], *,
                    batch_format: str = "numpy",
                    batch_size: Optional[int] = None,
                    compute: Optional["ActorPoolStrategy"] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None
                    ) -> "Dataset":
        """Per-block batch transform. A class UDF (or an explicit
        ``compute=ActorPoolStrategy(...)`` / ``concurrency=N``) runs on a
        pool of actors that construct the UDF once and reuse it per batch
        (reference: ``ActorPoolMapOperator``)."""
        if isinstance(fn, type) or compute is not None or                 concurrency is not None:
            pool = compute or ActorPoolStrategy(concurrency or 2)
            return self._with("map_batches_actors",
                              (fn, batch_format, pool.size,
                               fn_constructor_args,
                               fn_constructor_kwargs or {}))
        return self._with("map_batches", (fn, batch_format))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return self._with("filter", fn)

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return self._with("flat_map", fn)

    def limit(self, n: int) -> "Dataset":
        return self._with("limit", n)

    # ------------------------------------------------------------- execution
    def _execute(self) -> List[Any]:
        """Run pending stages with a streaming window; returns block refs.

        Pipelined: a block flows through all per-block stages without waiting
        for its siblings (the reference's operator fusion); `limit` cuts the
        stream short.
        """
        refs = list(self._block_refs)
        limit: Optional[int] = None
        stages = []
        for op, payload in self._plan:
            if op == "limit":
                limit = payload if limit is None else min(limit, payload)
            else:
                stages.append((op, payload))

        import uuid as _uuid

        stats_key = _uuid.uuid4().hex[:12]
        self._last_stats_key = stats_key
        try:  # session stats actor, shared across datasets
            ray_tpu.remote(_StatsActor).options(
                name=_STATS_ACTOR, get_if_exists=True,
                lifetime="detached").remote()
        except Exception:  # noqa: BLE001
            stats_key = None

        # Actor pools for stateful map_batches stages, one per stage;
        # torn down once every produced block is ready.
        pools: Dict[int, List[Any]] = {}

        def pool_for(stage_idx, payload):
            actors = pools.get(stage_idx)
            if actors is None:
                fn, _, size, ctor_args, ctor_kwargs = payload
                cls = ray_tpu.remote(_MapWorker)
                actors = [cls.remote(fn, ctor_args, ctor_kwargs)
                          for _ in builtins.range(size)]
                pools[stage_idx] = actors
            return actors

        rr = itertools.count()

        def apply_stages(ref):
            for i, (op, payload) in enumerate(stages):
                if op == "map":
                    ref = _map_block.remote(ref, payload, stats_key)
                elif op == "map_batches":
                    fn, fmt = payload
                    ref = _map_batches_block.remote(ref, fn, fmt, stats_key)
                elif op == "map_batches_actors":
                    actors = pool_for(i, payload)
                    actor = actors[next(rr) % len(actors)]
                    ref = actor.map_batch.remote(ref, payload[1], stats_key)
                elif op == "filter":
                    ref = _filter_block.remote(ref, payload, stats_key)
                elif op == "flat_map":
                    ref = _flat_map_block.remote(ref, payload, stats_key)
            return ref

        if not stages and limit is None:
            return refs

        # Resource-aware window: never hold more in-flight blocks than the
        # cluster can actually execute (2x CPUs), capped by MAX_IN_FLIGHT.
        try:
            cpus = ray_tpu.cluster_resources().get("CPU", 4.0)
        except Exception:  # noqa: BLE001
            cpus = 4.0
        window_cap = max(2, min(MAX_IN_FLIGHT, int(cpus * 2)))

        # Memory backpressure (reference: execution/backpressure_policy/):
        # when the cluster object store holds more than the budget, drain
        # the whole window before launching more block tasks — in-flight
        # outputs get consumed/freed instead of piling into a spill storm.
        mem_budget = int(os.environ.get(
            "RAY_TPU_DATA_MEMORY_BUDGET_BYTES", 2 << 30))
        mem_check = {"next": 0.0}

        def over_memory_budget() -> bool:
            now = time.monotonic()
            if now < mem_check["next"]:
                return False
            mem_check["next"] = now + 0.5  # probe at most 2x/sec
            try:
                from ray_tpu.util.state import memory_summary

                return memory_summary()["total_bytes"] > mem_budget
            except Exception:  # noqa: BLE001
                return False

        out = []
        window: List[Any] = []
        produced = 0
        for ref in refs:
            if limit is not None and produced >= limit:
                break
            window.append(apply_stages(ref))
            if len(window) >= window_cap or \
                    (window and over_memory_budget()):
                done = window.pop(0)
                # BLOCK until the oldest in-flight block finishes — without
                # this wait the window would only shuffle refs between
                # lists while every task launches at full speed.
                ray_tpu.wait([done], num_returns=1, timeout=None)
                out.append(done)
                if limit is not None:
                    produced += len(ray_tpu.get(done))
        for done in window:
            out.append(done)
            if limit is not None:
                produced += len(ray_tpu.get(done))
                if produced >= limit:
                    break
        if limit is not None:
            out = self._apply_limit(out, limit)
        if pools:
            all_actors = [a for lst in pools.values() for a in lst]
            final = list(out)

            def _teardown():
                try:
                    ray_tpu.wait(final, num_returns=len(final),
                                 timeout=3600)
                except Exception:  # noqa: BLE001
                    pass
                for a in all_actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:  # noqa: BLE001
                        pass

            import threading as _threading

            _threading.Thread(target=_teardown, daemon=True).start()
        return out

    @staticmethod
    def _apply_limit(refs: List[Any], n: int) -> List[Any]:
        out, total = [], 0
        for ref in refs:
            t: pa.Table = ray_tpu.get(ref)
            if total + len(t) <= n:
                out.append(ray_tpu.put(t))
                total += len(t)
            else:
                out.append(ray_tpu.put(t.slice(0, n - total)))
                total = n
            if total >= n:
                break
        return out

    def materialize(self) -> "Dataset":
        return Dataset(self._execute())

    # ------------------------------------------------------------ all-to-all
    # Two-stage task shuffle (reference: push-based shuffle —
    # ``data/_internal/planner/exchange/shuffle_task_spec.py`` map tasks +
    # reduce tasks streamed through the object store): each input block is
    # split into per-partition parts by a map task; each output block is
    # assembled by a reduce task. The driver holds only ObjectRefs and
    # (for sort) a small boundary sample — a dataset larger than driver
    # RAM shuffles fine.
    def _two_stage_shuffle(self, refs: List[Any], num_parts: int,
                           map_mode: str, map_arg, reduce_mode: str,
                           reduce_arg) -> "Dataset":
        parts = []
        for i, r in enumerate(refs):
            out = _shuffle_map.options(num_returns=num_parts).remote(
                r, num_parts, map_mode,
                map_arg(i) if callable(map_arg) else map_arg)
            parts.append([out] if num_parts == 1 else out)
        out_refs = [
            _shuffle_reduce.remote(
                reduce_mode,
                reduce_arg(j) if callable(reduce_arg) else reduce_arg,
                *[p[j] for p in parts])
            for j in builtins.range(num_parts)]
        ds = Dataset(out_refs)
        ds._last_shuffle = {"mode": "distributed", "map_tasks": len(refs),
                            "reduce_tasks": num_parts}
        return ds

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise combination of two row-aligned datasets (reference:
        ``Dataset.zip`` — duplicate right-side columns get a ``_1``
        suffix). The right side is sliced remotely to the left side's
        block boundaries; no blocks concentrate on the driver."""
        a_refs = self._execute()
        b_refs = other._execute()
        counts = ray_tpu.get(
            [_block_len.remote(r) for r in a_refs + b_refs], timeout=600)
        a_counts, b_counts = counts[:len(a_refs)], counts[len(a_refs):]
        if sum(a_counts) != sum(b_counts):
            raise ValueError(
                f"zip requires equal row counts; "
                f"got {sum(a_counts)} vs {sum(b_counts)}")
        out = []
        bi, b_off = 0, 0
        for a_ref, need in builtins.zip(a_refs, a_counts):
            pieces = []
            while need > 0:
                avail = b_counts[bi] - b_off
                take = min(need, avail)
                pieces.append(_slice_block.remote(b_refs[bi], b_off, take))
                b_off += take
                need -= take
                if b_off >= b_counts[bi]:
                    bi += 1
                    b_off = 0
            out.append(_zip_block.remote(a_ref, *pieces))
        return Dataset(out)

    def join(self, other: "Dataset", on, *, join_type: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join on key column(s) ``on`` (reference:
        ``Dataset.join``): both sides hash-partition by key (map stage),
        then each partition joins independently via Arrow's native join
        (reduce stage) — the same two-stage shape as the shuffle, so no
        driver-side concatenation."""
        if isinstance(on, str):
            on = [on]
        key = on[0]
        a_refs = self._execute()
        b_refs = other._execute()
        n = num_partitions or max(len(a_refs), len(b_refs))
        opts = {"num_returns": n} if n > 1 else {}
        a_parts = [_hash_partition_block.options(**opts).remote(r, key, n)
                   for r in a_refs]
        b_parts = [_hash_partition_block.options(**opts).remote(r, key, n)
                   for r in b_refs]
        if n == 1:
            a_parts = [[p] for p in a_parts]
            b_parts = [[p] for p in b_parts]
        out = [
            _join_reduce.remote(join_type, list(on), len(a_parts),
                                *[p[j] for p in a_parts],
                                *[p[j] for p in b_parts])
            for j in builtins.range(n)]
        return Dataset(out)

    def repartition(self, num_blocks: int) -> "Dataset":
        refs = self._execute()
        if not refs:
            return Dataset([ray_tpu.put(pa.table({}))
                            for _ in builtins.range(num_blocks)])
        # Order-preserving: fetch per-block row counts (scalars — the only
        # driver-side data), cut the global row range into num_blocks
        # contiguous spans, and have each map task zero-copy-slice its
        # block by global offset. Reduce tasks concat parts in input
        # order, so take_all() returns rows in the original order (the
        # previous concat-then-slice implementation preserved it too).
        sizes = ray_tpu.get([_block_len.remote(r) for r in refs],
                            timeout=600)
        total = sum(sizes)
        cuts = [total * (j + 1) // num_blocks
                for j in builtins.range(num_blocks - 1)]
        starts = list(itertools.accumulate([0] + sizes[:-1]))
        return self._two_stage_shuffle(
            refs, num_blocks, "slice", lambda i: (starts[i], cuts),
            "concat", None)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        refs = self._execute()
        if not refs:
            return Dataset([])
        k = len(refs)
        map_arg = (lambda i: (seed, i)) if seed is not None else \
            (lambda i: None)
        reduce_arg = (lambda j: (seed, 1 << 20, j)) if seed is not None \
            else (lambda j: None)
        return self._two_stage_shuffle(refs, k, "random", map_arg,
                                       "random", reduce_arg)

    SORT_SAMPLES_PER_BLOCK = 64

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        refs = self._execute()
        if not refs:
            return Dataset([])
        k = len(refs)
        if k == 1:
            order = "descending" if descending else "ascending"
            return Dataset([_shuffle_reduce.remote(
                "sort", (key, order), refs[0])])
        # Range partitioning (TeraSort shape): sample keys per block (the
        # ONLY driver-side materialization — dozens of scalars per block),
        # cut boundaries at sample quantiles, then map-split by range and
        # reduce-sort each range locally.
        samples = ray_tpu.get(
            [_sample_keys.remote(r, key, self.SORT_SAMPLES_PER_BLOCK)
             for r in refs], timeout=600)
        live = [s for s in samples if len(s)]
        if not live:  # every block is empty: nothing to sort
            return self.repartition(k)
        allv = np.sort(np.concatenate(live))
        bounds = [allv[min(int(j * len(allv) / k), len(allv) - 1)]
                  for j in builtins.range(1, k)]
        order = "descending" if descending else "ascending"
        ds = self._two_stage_shuffle(
            refs, k, "range", (key, bounds), "sort", (key, order))
        if descending:
            # Range partitions are ascending; a descending sort reads
            # the partitions in reverse.
            ds._block_refs = list(reversed(ds._block_refs))
        return ds

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        refs = self._execute()
        for o in others:
            refs = refs + o._execute()
        return Dataset(refs)

    def split(self, n: int) -> List["Dataset"]:
        refs = self.repartition(n)._block_refs
        return [Dataset([r]) for r in refs]

    def streaming_split(self, n: int,
                        name: Optional[str] = None) -> List["DataIterator"]:
        """Per-consumer iterators for Train ingest (reference:
        ``Dataset.streaming_split`` feeding ray.train workers).
        ``name`` tags each shard's ingest telemetry (JaxTrainer passes
        its ``datasets=`` key)."""
        parts = self.split(n)
        return [DataIterator(p, name=name) for p in parts]

    def iterator(self) -> "DataIterator":
        return DataIterator(self)

    # ------------------------------------------------------------ consumers
    def count(self) -> int:
        return sum(len(t) for t in ray_tpu.get(self._execute()))

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref in self._execute():
            for row in _rows_of(ray_tpu.get(ref)):
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref in self._execute():
            out.extend(_rows_of(ray_tpu.get(ref)))
        return out

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._execute():
            yield from _rows_of(ray_tpu.get(ref))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Batch]:
        pending: Optional[pa.Table] = None
        for ref in self._execute():
            t = ray_tpu.get(ref)
            if pending is not None and len(pending):
                t = pa.concat_tables([pending, t]) if len(t) else pending
                pending = None
            off = 0
            while off + batch_size <= len(t):
                yield _batch_of(t.slice(off, batch_size), batch_format)
                off += batch_size
            if off < len(t):
                pending = t.slice(off)
        if pending is not None and len(pending) and not drop_last:
            yield _batch_of(pending, batch_format)

    def to_pandas(self):
        tables = ray_tpu.get(self._execute())
        live = [t for t in tables if len(t)]
        return (pa.concat_tables(live) if live else pa.table({})).to_pandas()

    # ------------------------------------------------------------- writers
    # Distributed writes (reference: ``Dataset.write_parquet`` etc. —
    # one output file per block, written by the task that holds the
    # block; the driver only collects the written paths).
    def _write(self, dir_path: str, fmt: str, ext: str) -> List[str]:
        os.makedirs(dir_path, exist_ok=True)
        refs = self._execute()
        out = [
            _write_block.remote(
                r, os.path.join(dir_path, f"block_{i:05d}.{ext}"), fmt)
            for i, r in enumerate(refs)]
        return [p for p in ray_tpu.get(out, timeout=600) if p]

    def write_parquet(self, dir_path: str) -> List[str]:
        """Write one parquet file per block into ``dir_path``; returns
        the written paths (empty blocks are skipped)."""
        return self._write(dir_path, "parquet", "parquet")

    def write_csv(self, dir_path: str) -> List[str]:
        return self._write(dir_path, "csv", "csv")

    def write_json(self, dir_path: str) -> List[str]:
        """Newline-delimited JSON, one file per block."""
        return self._write(dir_path, "json", "jsonl")

    def schema(self):
        for ref in self._execute():
            t = ray_tpu.get(ref)
            if t.num_columns:
                return t.schema
        return None

    def stats(self) -> str:
        """Per-operator execution stats of the last run (reference:
        ``Dataset.stats()`` / ``data/_internal/stats.py``)."""
        key = self._last_stats_key
        if key is None:
            return "(dataset not executed yet)"
        try:
            data = ray_tpu.get(
                ray_tpu.get_actor(_STATS_ACTOR).get.remote(key), timeout=10)
        except Exception:  # noqa: BLE001
            return "(no stats recorded)"
        if not data:
            return "(no stats recorded)"
        lines = []
        for op, (rin, rout, secs, blocks) in data.items():
            lines.append(
                f"{op}: {blocks} blocks, {rin} rows in -> {rout} rows out, "
                f"{secs * 1000:.1f}ms total wall")
        return "\n".join(lines)

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def __repr__(self):
        return f"Dataset(num_blocks={len(self._block_refs)}, plan={len(self._plan)} stages)"


def _split_table(t: pa.Table, k: int) -> List[pa.Table]:
    n = len(t)
    sizes = [n // k + (1 if i < n % k else 0) for i in builtins.range(k)]
    out, off = [], 0
    for s in sizes:
        out.append(t.slice(off, s))
        off += s
    return out


# ------------------------------------------------------- shuffle task bodies
@ray_tpu.remote
def _shuffle_map(table: pa.Table, num_parts: int, mode: str, arg):
    """Map stage: split one block into ``num_parts`` partition tables.

    Runs on workers; the driver only routes the returned refs to reduce
    tasks (reference: shuffle map tasks,
    ``data/_internal/planner/exchange/shuffle_task_spec.py``).
    """
    n = len(table)
    if mode == "slice":
        # Contiguous split by global row offset (order-preserving
        # repartition): partition j covers global rows [cuts[j-1], cuts[j]).
        start, cuts = arg
        edges = [0] + [min(max(c - start, 0), n) for c in cuts] + [n]
        parts = tuple(table.slice(edges[j], edges[j + 1] - edges[j])
                      for j in builtins.range(num_parts))
        return parts if num_parts > 1 else parts[0]
    if mode == "roundrobin":
        groups = [np.arange(j, n, num_parts)
                  for j in builtins.range(num_parts)]
    elif mode == "random":
        assign = np.random.default_rng(arg).integers(0, num_parts, size=n)
        groups = [np.nonzero(assign == j)[0]
                  for j in builtins.range(num_parts)]
    elif mode == "range":
        key, bounds = arg
        values = table.column(key).to_numpy(zero_copy_only=False) if n \
            else np.array([])
        part_ids = np.searchsorted(np.asarray(bounds), values,
                                   side="right") if n else values
        groups = [np.nonzero(part_ids == j)[0]
                  for j in builtins.range(num_parts)]
    else:
        raise ValueError(f"unknown shuffle map mode {mode!r}")
    parts = tuple(
        table.take(pa.array(g)) if len(g) else table.slice(0, 0)
        for g in groups)
    return parts if num_parts > 1 else parts[0]


@ray_tpu.remote
def _shuffle_reduce(mode: str, arg, *parts: pa.Table) -> pa.Table:
    """Reduce stage: assemble one output block from its per-map parts."""
    live = [t for t in parts if len(t)]
    combined = pa.concat_tables(live) if live else \
        (parts[0].slice(0, 0) if parts else pa.table({}))
    if mode == "random" and len(combined):
        idx = np.random.default_rng(arg).permutation(len(combined))
        combined = combined.take(pa.array(idx))
    elif mode == "sort" and len(combined):
        key, order = arg
        combined = combined.sort_by([(key, order)])
    return combined


@ray_tpu.remote
def _block_len(table: pa.Table) -> int:
    return len(table)


@ray_tpu.remote
def _write_block(table: pa.Table, path: str, fmt: str) -> str:
    """Write one block to one file (runs on the worker holding it).
    Returns the path, or "" for an empty block (no file emitted)."""
    if not len(table):
        return ""
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(table, path)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(table, path)
    elif fmt == "json":
        import json as _json

        with open(path, "w") as f:
            for row in table.to_pylist():
                f.write(_json.dumps(row) + "\n")
    else:
        raise ValueError(f"unknown write format {fmt!r}")
    return path


@ray_tpu.remote
def _agg_map(table: pa.Table, key: str, col: str, how: str) -> pa.Table:
    """Per-block partial aggregate. ``mean`` ships (sum, count) partials
    so the reduce can re-combine exactly."""
    if not len(table):
        return table.slice(0, 0)
    if how == "mean":
        return table.group_by(key).aggregate([(col, "sum"), (col, "count")])
    return table.group_by(key).aggregate([(col, how)])


@ray_tpu.remote
def _agg_reduce(key: str, col: str, how: str, *parts: pa.Table) -> pa.Table:
    """Re-aggregate partials into the final grouped table (column naming
    matches a single-pass ``group_by(key).aggregate([(col, how)])``)."""
    import pyarrow.compute as pc

    live = [t for t in parts if len(t)]
    if not live:
        return pa.table({})
    combined = pa.concat_tables(live)
    if how == "mean":
        g = combined.group_by(key).aggregate(
            [(f"{col}_sum", "sum"), (f"{col}_count", "sum")])
        mean = pc.divide(
            pc.cast(g[f"{col}_sum_sum"], pa.float64()),
            pc.cast(g[f"{col}_count_sum"], pa.float64()))
        return pa.table({key: g[key], f"{col}_mean": mean})
    recombine = "sum" if how in ("sum", "count") else how
    g = combined.group_by(key).aggregate([(f"{col}_{how}", recombine)])
    out = {key: g[key], f"{col}_{how}": g[f"{col}_{how}_{recombine}"]}
    return pa.table(out)


@ray_tpu.remote
def _sample_keys(table: pa.Table, key: str, k: int):
    """Sort-boundary sampling: at most ``k`` key values from one block."""
    if key not in table.column_names:  # schema-less empty block
        return np.array([])
    values = table.column(key).to_numpy(zero_copy_only=False)
    if len(values) <= k:
        return values
    idx = np.random.default_rng(len(values)).choice(len(values), size=k,
                                                    replace=False)
    return values[idx]


class GroupedData:
    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, col: str, how: str) -> Dataset:
        # Distributed combine: per-block partial aggregates on workers,
        # one reduce task re-aggregates the partials (reference:
        # ``data/_internal/planner/exchange/aggregate_task_spec.py``).
        # The driver never holds the dataset.
        refs = self._ds._execute()
        if not refs:
            return Dataset([ray_tpu.put(pa.table({}))])
        partials = [_agg_map.remote(r, self._key, col, how) for r in refs]
        return Dataset([_agg_reduce.remote(self._key, col, how, *partials)])

    def sum(self, col: str) -> Dataset:
        return self._agg(col, "sum")

    def mean(self, col: str) -> Dataset:
        return self._agg(col, "mean")

    def min(self, col: str) -> Dataset:
        return self._agg(col, "min")

    def max(self, col: str) -> Dataset:
        return self._agg(col, "max")

    def count(self) -> Dataset:
        return self._agg(self._key, "count")


class DataIterator:
    """Reference: ``ray.data.DataIterator`` handed to train workers."""

    def __init__(self, ds: Dataset, name: Optional[str] = None):
        self._ds = ds
        # Ingest-telemetry tag (streaming_split passes JaxTrainer's
        # datasets= key) so train/eval pipelines don't alias onto one
        # iterator label.
        self._name = name

    def iter_batches(self, **kw) -> Iterator[Batch]:
        return self._ds.iter_batches(**kw)

    def iter_device_batches(self, sharding=None, *, prefetch: int = 2,
                            decode_fn=None, name: Optional[str] = None,
                            **kw):
        """Mesh-staged batches with background prefetch ON BY DEFAULT:
        host decode + sharded ``jax.device_put`` run on a prefetch
        thread through a ``prefetch``-deep buffer, so batch N+1's H2D
        transfer overlaps step N (see
        :class:`ray_tpu.train.ingest.DevicePrefetcher`). ``sharding``
        is a NamedSharding or anything carrying ``batch_sharding``
        (e.g. a ShardedTrainer); remaining kwargs go to
        :meth:`iter_batches`. ``drop_last`` defaults to True HERE
        (unlike host iter_batches): the jitted train_step holds one
        compiled signature, so a ragged tail batch would retrace — or
        fail the microbatch-divisibility check outright."""
        from ray_tpu.train.ingest import DevicePrefetcher

        kw.setdefault("drop_last", True)
        return DevicePrefetcher(self.iter_batches(**kw), sharding,
                                depth=prefetch, decode_fn=decode_fn,
                                name=name or self._name or "train")

    def iter_rows(self):
        return self._ds.iter_rows()

    def materialize(self):
        return self._ds.materialize()


# ----------------------------------------------------------------- creation
def from_items(items: Sequence[Any], *, parallelism: int = 8) -> Dataset:
    items = list(items)
    k = max(1, min(parallelism, len(items) or 1))
    chunk = (len(items) + k - 1) // k
    refs = [ray_tpu.put(_table_from_rows(items[i:i + chunk]))
            for i in builtins.range(0, max(len(items), 1), chunk)]
    return Dataset(refs)


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    k = max(1, min(parallelism, n or 1))
    sizes = [n // k + (1 if i < n % k else 0) for i in builtins.range(k)]
    refs, off = [], 0
    for s in sizes:
        refs.append(ray_tpu.put(
            pa.table({"id": np.arange(off, off + s, dtype=np.int64)})))
        off += s
    return Dataset(refs)


def from_numpy(arr: np.ndarray, *, parallelism: int = 8,
               column: str = "data") -> Dataset:
    """Multi-dim arrays become tensor columns: ``iter_batches`` yields
    the exact [N, d1, ...] ndarray back, mesh-shardable without copies."""
    parts = np.array_split(arr, max(1, parallelism))
    refs = [ray_tpu.put(_table_from_batch({column: p}))
            for p in parts if len(p)]
    return Dataset(refs)


def from_pandas(df) -> Dataset:
    return Dataset([ray_tpu.put(pa.Table.from_pandas(df,
                                                     preserve_index=False))])


def from_arrow(table: pa.Table) -> Dataset:
    return Dataset([ray_tpu.put(table)])


def _expand_paths(paths) -> List[str]:
    """Files from a path / glob / directory / list thereof."""
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, fnames in os.walk(p):
                dirs.sort()  # deterministic traversal order
                files.extend(os.path.join(root, f) for f in sorted(fnames))
            continue
        matches = sorted(glob_mod.glob(p))
        files.extend(matches if matches else [p])
    return files


# File readers: thin wrappers over the Datasource interface (reference:
# ``python/ray/data/read_api.py`` delegating to datasource classes) —
# custom sources use ``ray_tpu.data.read_datasource`` with the same
# machinery.
def read_parquet(paths, *, parallelism: int = 8) -> Dataset:
    from ray_tpu.data.datasource import ParquetDatasource, read_datasource

    return read_datasource(ParquetDatasource(paths),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: int = 8) -> Dataset:
    from ray_tpu.data.datasource import CSVDatasource, read_datasource

    return read_datasource(CSVDatasource(paths), parallelism=parallelism)


def read_json(paths, *, parallelism: int = 8) -> Dataset:
    from ray_tpu.data.datasource import JSONDatasource, read_datasource

    return read_datasource(JSONDatasource(paths), parallelism=parallelism)


def read_binary_files(paths, *, include_paths: bool = True,
                      parallelism: int = 8) -> Dataset:
    """One row per file: ``{"bytes": ..., "path": ...}`` (reference:
    ``ray.data.read_binary_files`` — the raw-ingest entry point image/audio
    pipelines decode with ``map``)."""
    from ray_tpu.data.datasource import (BinaryFilesDatasource,
                                         read_datasource)

    return read_datasource(BinaryFilesDatasource(paths, include_paths),
                           parallelism=parallelism)


def read_text(paths, *, parallelism: int = 8) -> Dataset:
    """One row per line: ``{"text": ...}`` (reference:
    ``ray.data.read_text``)."""
    from ray_tpu.data.datasource import TextDatasource, read_datasource

    return read_datasource(TextDatasource(paths), parallelism=parallelism)
