"""Custom data sources: the ``Datasource`` interface.

Reference: ``python/ray/data/datasource/datasource.py:11`` — a datasource
turns itself into a list of *read tasks*; each task runs remotely and
produces one block (an Arrow table). The five built-in file readers
(`read_parquet`/`read_csv`/`read_json`/`read_binary_files`/`read_text`)
are reimplemented on this interface, and users plug in anything (object
stores, databases, synthetic generators) by subclassing.
"""

from __future__ import annotations

import abc
from typing import Callable, List

import pyarrow as pa

import ray_tpu


class Datasource(abc.ABC):
    """A pluggable source of blocks.

    Implement :meth:`get_read_tasks` to return up to ``parallelism``
    zero-argument callables; each is executed as ONE remote task and must
    return a ``pyarrow.Table`` block. Tasks must be picklable (top-level
    functions / functools.partial / dataclass instances — the usual
    cloudpickle rules).
    """

    @abc.abstractmethod
    def get_read_tasks(self, parallelism: int) \
            -> List[Callable[[], pa.Table]]:
        ...

    def estimate_inmemory_data_size(self) -> int:
        """Optional size hint (bytes); -1 = unknown."""
        return -1


@ray_tpu.remote
def _run_read_task(task) -> pa.Table:
    out = task()
    if not isinstance(out, pa.Table):
        raise TypeError(
            f"read task must return a pyarrow.Table, got "
            f"{type(out).__name__}")
    return out


def read_datasource(source: Datasource, *, parallelism: int = 8):
    """Materialize a :class:`Datasource` into a Dataset: one remote task
    per read task, blocks stay in the object store."""
    from ray_tpu.data.dataset import Dataset

    tasks = source.get_read_tasks(parallelism)
    if not tasks:
        return Dataset([ray_tpu.put(pa.table({}))])
    return Dataset([_run_read_task.remote(t) for t in tasks])


# --------------------------------------------------------------- builtins
class _FileDatasource(Datasource):
    """Shared scaffold: expand paths, stride into ≤parallelism groups,
    one read task per group."""

    def __init__(self, paths):
        self.paths = paths

    def get_read_tasks(self, parallelism: int):
        from functools import partial

        from ray_tpu.data.dataset import _expand_paths

        files = _expand_paths(self.paths)
        groups = [g for i in range(max(1, parallelism))
                  if (g := files[i::max(1, parallelism)])]
        return [partial(self._read_group, g) for g in groups]

    @abc.abstractmethod
    def _read_group(self, group: List[str]) -> pa.Table:
        ...


class ParquetDatasource(_FileDatasource):
    def _read_group(self, group):
        import pyarrow.parquet as pq

        tables = [pq.read_table(p) for p in group]
        return pa.concat_tables(tables) if tables else pa.table({})


class CSVDatasource(_FileDatasource):
    def _read_group(self, group):
        from pyarrow import csv as pa_csv

        tables = [pa_csv.read_csv(p) for p in group]
        return pa.concat_tables(tables) if tables else pa.table({})


class JSONDatasource(_FileDatasource):
    def _read_group(self, group):
        from pyarrow import json as pa_json

        tables = [pa_json.read_json(p) for p in group]
        return pa.concat_tables(tables) if tables else pa.table({})


class BinaryFilesDatasource(_FileDatasource):
    """One row per file: ``{"bytes": ..., "path": ...}``."""

    def __init__(self, paths, include_paths: bool = True):
        super().__init__(paths)
        self.include_paths = include_paths

    def _read_group(self, group):
        rows = {"bytes": []}
        if self.include_paths:
            rows["path"] = []
        for path in group:
            with open(path, "rb") as f:
                rows["bytes"].append(f.read())
            if self.include_paths:
                rows["path"].append(path)
        return pa.table(rows)


class TextDatasource(_FileDatasource):
    """One row per line: ``{"text": ...}``."""

    def _read_group(self, group):
        lines = []
        for path in group:
            with open(path, encoding="utf-8") as f:
                # Only \n terminates rows (str.splitlines would also
                # split on unicode separators); rstrip handles CRLF.
                lines.extend(line.rstrip("\r\n") for line in f)
        return pa.table({"text": lines})


__all__ = [
    "Datasource", "read_datasource", "ParquetDatasource", "CSVDatasource",
    "JSONDatasource", "BinaryFilesDatasource", "TextDatasource",
]
