"""ray_tpu.train: distributed training library (reference: ``python/ray/train``).

TorchTrainer-shaped API whose backend is jax: workers jointly run one SPMD
program over a device mesh; DP/FSDP/TP/SP are sharding-rule choices
(:mod:`ray_tpu.models.training`), not module wrappers.
"""

from ray_tpu.train.backend_executor import (
    BackendExecutor,
    JaxBackend,
    TrainWorker,
    WorkerGroup,
)
from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    load_pytree,
    save_pytree,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.elastic import ResizeGuard, request_resize
from ray_tpu.train.goodput import GoodputLedger, StragglerDetector
from ray_tpu.train.ingest import DevicePrefetcher, prefetch_to_device
from ray_tpu.train.loop import AsyncStepLoop
from ray_tpu.train.session import (
    get_checkpoint,
    get_checkpoint_plane,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.storage import AsyncCheckpointer, StorageContext
from ray_tpu.train.trainer import ControllerState, JaxTrainer

__all__ = [
    "AsyncCheckpointer", "AsyncStepLoop", "BackendExecutor", "Checkpoint",
    "CheckpointConfig", "CheckpointManager", "ControllerState",
    "DevicePrefetcher", "FailureConfig", "GoodputLedger", "JaxBackend",
    "JaxTrainer", "ResizeGuard", "Result", "RunConfig", "ScalingConfig",
    "StorageContext", "StragglerDetector", "TrainWorker", "WorkerGroup",
    "get_checkpoint",
    "get_checkpoint_plane", "get_context", "get_dataset_shard",
    "load_pytree", "prefetch_to_device", "report", "request_resize",
    "save_pytree",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("train")
del _rlu
