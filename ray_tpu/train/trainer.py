"""JaxTrainer: the TorchTrainer-shaped entry point for distributed training.

Reference: ``train/torch/torch_trainer.py:11`` + ``DataParallelTrainer``
(``train/data_parallel_trainer.py``) + the controller loop of
``train/v2/_internal/execution/controller/controller.py:85``. The fit loop:
start worker group → run ``train_loop_per_worker`` on every worker → poll the
session queues for reported metrics/checkpoints → persist checkpoints (top-k)
→ on worker failure, restart the group from the latest checkpoint while
``FailureConfig.max_failures`` allows (reference ``backend_executor.py:705``).
"""

from __future__ import annotations

import logging
import os
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.train.backend_executor import BackendExecutor, JaxBackend
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)

logger = logging.getLogger(__name__)


class ControllerState:
    """Controller lifecycle states (reference: Train v2 controller state
    machine, ``train/v2/_internal/execution/controller/controller.py:85``)."""

    INITIALIZING = "INITIALIZING"
    SCHEDULING = "SCHEDULING"
    RUNNING = "RUNNING"
    RESTARTING = "RESTARTING"
    FINISHED = "FINISHED"
    ERRORED = "ERRORED"


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend: Optional[JaxBackend] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend = backend
        self.resume_from_checkpoint = resume_from_checkpoint
        # Train ingest (reference: DataParallelTrainer datasets= +
        # ray.train.get_dataset_shard): each named ray_tpu.data.Dataset
        # is streaming_split into DISJOINT per-worker shards at (re)start
        # — elastic restarts re-split over the surviving worker count.
        self.datasets = datasets
        self.controller_state = ControllerState.INITIALIZING
        self.state_history: List[str] = [ControllerState.INITIALIZING]

    def _set_state(self, state: str) -> None:
        if state != self.controller_state:
            logger.info("train controller: %s -> %s",
                        self.controller_state, state)
            self.controller_state = state
            self.state_history.append(state)

    def _elastic_worker_target(self) -> int:
        """How many workers to (re)start with: the full ask when rigid, or
        whatever the cluster can currently supply down to ``min_workers``
        when elastic (reference: Train v2 elastic resizing on recovery)."""
        sc = self.scaling_config
        want = sc.num_workers
        floor = sc.min_workers if sc.min_workers is not None else want
        if floor >= want:
            return want
        try:
            avail = ray_tpu.available_resources()
        except Exception:  # noqa: BLE001
            return want
        # Feasibility is the min over EVERY resource the worker asks for
        # (a CPU-only estimate would still deadlock TPU-constrained jobs).
        feasible = want
        for key, per in sc.worker_resources().items():
            if per > 0:
                feasible = min(feasible,
                               int(avail.get(key, 0.0) // per))
        return max(min(want, feasible), floor)

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        rc = self.run_config
        storage_path = rc.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        name = rc.name or f"JaxTrainer_{int(time.time())}"
        storage = None
        if "://" in storage_path:
            # Cloud-fs persistence (reference StorageContext): the run's
            # working dir stays local; checkpoints mirror to the pyarrow
            # filesystem behind the URI.
            from ray_tpu.train.storage import StorageContext

            storage = StorageContext(storage_path, name)
            exp_dir = os.path.join(tempfile.gettempdir(),
                                   "ray_tpu_results", name)
        else:
            exp_dir = os.path.join(storage_path, name)
        os.makedirs(exp_dir, exist_ok=True)

        ckpt_cfg: CheckpointConfig = rc.checkpoint_config
        manager = CheckpointManager(
            os.path.join(exp_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
            async_write=ckpt_cfg.async_write,
            storage=storage,
        )

        failure_cfg: FailureConfig = rc.failure_config
        failures = 0
        preemptions = 0
        # Preemptions are routine on TPU pods, not failures: they get
        # their own (generous) budget instead of consuming max_failures.
        max_preemptions = int(os.environ.get(
            "RAY_TPU_MAX_PREEMPTIONS", 64))
        restore: Optional[Checkpoint] = self.resume_from_checkpoint
        latest_metrics: Optional[Dict[str, Any]] = None
        history: List[Dict[str, Any]] = []
        error: Optional[BaseException] = None

        while True:
            self._set_state(ControllerState.SCHEDULING)
            target = self._elastic_worker_target()
            scaling = self.scaling_config
            if target != scaling.num_workers:
                import dataclasses as _dc

                logger.warning(
                    "elastic training: starting with %d/%d workers "
                    "(min_workers=%s)", target, scaling.num_workers,
                    scaling.min_workers)
                scaling = _dc.replace(scaling, num_workers=target)
            executor = BackendExecutor(scaling, self.backend)
            executor.start()
            worker_datasets = None
            if self.datasets:
                worker_datasets = [
                    {} for _ in range(scaling.num_workers)]
                for ds_name, ds in self.datasets.items():
                    shards = ds.streaming_split(scaling.num_workers,
                                                name=ds_name)
                    for rank, it in enumerate(shards):
                        worker_datasets[rank][ds_name] = it
            run_refs = executor.start_training(
                self.train_loop, self.train_loop_config,
                restore.path if restore else None, run_dir=exp_dir,
                datasets=worker_datasets)
            self._set_state(ControllerState.RUNNING)
            try:
                self._drive(executor, run_refs, manager, history)
                latest_metrics = history[-1]["metrics"] if history else None
                error = None
                executor.shutdown()
                self._set_state(ControllerState.FINISHED)
                break
            except exceptions.PreemptedError as e:
                # A worker host is going away (SIGTERM / maintenance
                # event): the loop already ran its just-in-time save, so
                # restart and resume from the newest COMMITTED manifest
                # — the checkpoint plane guarantees readers never see the
                # half-written one (see ray_tpu/checkpoint/plane.py).
                executor.shutdown()
                preemptions += 1
                if preemptions > max_preemptions:
                    error = e
                    latest_metrics = history[-1]["metrics"] if history else None
                    self._set_state(ControllerState.ERRORED)
                    break
                self._set_state(ControllerState.RESTARTING)
                try:
                    manager.flush()
                except Exception as persist_err:  # noqa: BLE001
                    logger.warning("checkpoint persist failed (%s); "
                                   "restoring from the previous one",
                                   persist_err)
                restore = manager.latest or restore
                logger.warning(
                    "worker preempted (%s); resuming from the newest "
                    "committed checkpoint (preemption %d/%d)",
                    e.reason, preemptions, max_preemptions)
            except (exceptions.RayTaskError, exceptions.ActorDiedError,
                    exceptions.WorkerCrashedError) as e:
                executor.shutdown()
                failures += 1
                recoverable = (failure_cfg.max_failures < 0
                               or failures <= failure_cfg.max_failures)
                if not recoverable:
                    error = e
                    latest_metrics = history[-1]["metrics"] if history else None
                    self._set_state(ControllerState.ERRORED)
                    break
                self._set_state(ControllerState.RESTARTING)
                try:
                    # Restore only from fully-persisted dirs; a failed
                    # async persist drops its entry and must not abort
                    # the recovery it exists to serve.
                    manager.flush()
                except Exception as persist_err:  # noqa: BLE001
                    logger.warning("checkpoint persist failed (%s); "
                                   "restoring from the previous one",
                                   persist_err)
                restore = manager.latest or restore
                logger.warning(
                    "Training attempt %d failed (%s); restarting from %s",
                    failures, e,
                    restore.path if restore else "scratch")

        try:
            manager.close()
        except Exception as persist_err:  # noqa: BLE001
            logger.warning("final checkpoint persist failed: %s",
                           persist_err)
        return Result(
            metrics=latest_metrics,
            checkpoint=manager.best,
            path=exp_dir,
            error=error,
            metrics_history=history,
        )

    # ------------------------------------------------------------------
    def _drive(self, executor: BackendExecutor, run_refs,
               manager: CheckpointManager, history: List[Dict[str, Any]]):
        """Poll session queues until every worker's run() completes."""
        from ray_tpu._private import metrics_defs as mdefs

        mtags = {"trainer": type(self).__name__}
        last_report_ts = 0.0

        def observe_round(metrics, nreports):
            """Per-step observability: report cadence is the step cadence
            (reference: workers report once per step), so the wall time
            since the previous poll round, split across the ``nreports``
            steps merged this round, is the per-step time — recording the
            raw inter-call gap would log ~0s for every buffered report
            when steps back up. A tokens_per_s metric key feeds the
            throughput gauge."""
            nonlocal last_report_ts
            now = time.monotonic()
            mdefs.TRAIN_REPORTS.inc(nreports, tags=mtags)
            if last_report_ts:
                per_step = (now - last_report_ts) / nreports
                for _ in range(nreports):
                    mdefs.TRAIN_STEP_SECONDS.observe(per_step, tags=mtags)
            last_report_ts = now
            tps = (metrics or {}).get("tokens_per_s")
            if isinstance(tps, (int, float)):
                mdefs.TRAIN_TOKENS_PER_S.set(float(tps), tags=mtags)

        while True:
            polls = executor.poll()
            # Merge this round's reports: workers report at the same cadence;
            # rank 0's metrics win, any rank's checkpoint is persisted
            # (reference keeps rank-0 checkpoints by default).
            max_reports = max((len(p["reports"]) for p in polls), default=0)
            for i in range(max_reports):
                metrics = None
                ckpt_path = None
                for rank, p in enumerate(polls):
                    if i < len(p["reports"]):
                        r = p["reports"][i]
                        if metrics is None:
                            metrics = r["metrics"]
                        if ckpt_path is None and r.get("checkpoint_path"):
                            ckpt_path = r["checkpoint_path"]
                entry: Dict[str, Any] = {"metrics": metrics}
                if ckpt_path:
                    persisted = manager.register(
                        Checkpoint(ckpt_path), metrics or {})
                    entry["checkpoint"] = persisted
                history.append(entry)
            if max_reports:
                observe_round(metrics, max_reports)

            done, _ = ray_tpu.wait(run_refs, num_returns=len(run_refs),
                                   timeout=0.02)
            if len(done) == len(run_refs):
                # Raises through to fit() on worker failure.
                ray_tpu.get(run_refs)
                # Final drain.
                final = executor.poll()
                for rank, p in enumerate(final):
                    for r in p["reports"]:
                        entry = {"metrics": r["metrics"]}
                        if r.get("checkpoint_path"):
                            entry["checkpoint"] = manager.register(
                                Checkpoint(r["checkpoint_path"]),
                                r["metrics"] or {})
                        history.append(entry)
                return
