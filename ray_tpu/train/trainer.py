"""JaxTrainer: the TorchTrainer-shaped entry point for distributed training.

Reference: ``train/torch/torch_trainer.py:11`` + ``DataParallelTrainer``
(``train/data_parallel_trainer.py``) + the controller loop of
``train/v2/_internal/execution/controller/controller.py:85``. The fit loop:
start worker group → run ``train_loop_per_worker`` on every worker → poll the
session queues for reported metrics/checkpoints → persist checkpoints (top-k)
→ on worker failure, restart the group from the latest checkpoint while
``FailureConfig.max_failures`` allows (reference ``backend_executor.py:705``).

Elastic fault tolerance (reference: Train v2 elastic worker groups): every
attempt-ending exception is classified (``ray_tpu/train/elastic.py``) and
charged to the matching budget —

* **worker_lost / hang** (actor death, lapsed heartbeats, step-watchdog
  timeout): retried under ``RAY_TPU_MAX_RESTARTS`` with exponential
  backoff (``RAY_TPU_RESTART_BACKOFF_S`` base, doubling per consecutive
  zero-progress attempt, capped at ``RAY_TPU_RESTART_BACKOFF_MAX_S``);
* **preemption**: ``RAY_TPU_MAX_PREEMPTIONS``, immediate restart;
* **resize** (world-target hints on the preemption pubsub channel, or a
  grow-back opening detected via the periodic ``RAY_TPU_GROW_CHECK_S``
  feasibility probe / the GCS capacity-grew hint): ``RAY_TPU_MAX_RESIZES``,
  immediate restart at the new world size;
* **user** exceptions: ``FailureConfig.max_failures``, unchanged;
* **fatal** (repeated-NaN loss, jax.distributed bootstrap failure): the
  run errors out without consuming any retry budget.

Each restart re-acquires workers (fewer or more), re-forms the mesh at the
new world size (the loop reads ``get_context().get_world_size()``), and
resumes from the newest committed checkpoint-plane manifest. Every
recovery is appended to ``JaxTrainer.recovery_log`` and mirrored to the
``ray_tpu_train_restarts_total{cause}`` / ``ray_tpu_train_world_size`` /
``ray_tpu_train_recovery_seconds`` metrics.

Training-path observability (the train-side twin of the serve request
plane, ``ray_tpu/train/goodput.py``):

* **goodput ledger** — every attempt's wall clock, partitioned into
  step / input_stall / sync / ckpt_block / recovery worker-side;
  controller differences rank-0 snapshots into
  ``ray_tpu_train_goodput_seconds_total{component}`` and keeps exact
  per-attempt entries in ``JaxTrainer.goodput_log``;
* **per-rank step timelines** — each report carries its step's wall
  time; the controller merges them into fixed-size windows, feeds
  ``ray_tpu_train_rank_step_seconds{rank}``, and flags stragglers
  (``ray_tpu_train_straggler{rank}``, GCS ``__train__`` KV, log);
* **one connected trace per run** (``RAY_TPU_TRACING=1``) —
  ``train.run`` → ``train.attempt`` → ``train.step_window`` spans plus
  a ``train.recovery`` tree per elastic recovery whose duration equals
  the recovery metric; ``ray-tpu trace train <run>`` reconstructs it.
"""

from __future__ import annotations

import logging
import math
import os
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.train import elastic, goodput
from ray_tpu.train.backend_executor import (
    TRAIN_KV_NS,
    BackendExecutor,
    JaxBackend,
)
from ray_tpu.train.goodput import _env_float, _env_int
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)

logger = logging.getLogger(__name__)


class ControllerState:
    """Controller lifecycle states (reference: Train v2 controller state
    machine, ``train/v2/_internal/execution/controller/controller.py:85``)."""

    INITIALIZING = "INITIALIZING"
    SCHEDULING = "SCHEDULING"
    RUNNING = "RUNNING"
    RESTARTING = "RESTARTING"
    FINISHED = "FINISHED"
    ERRORED = "ERRORED"


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend: Optional[JaxBackend] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend = backend
        self.resume_from_checkpoint = resume_from_checkpoint
        # Train ingest (reference: DataParallelTrainer datasets= +
        # ray.train.get_dataset_shard): each named ray_tpu.data.Dataset
        # is streaming_split into DISJOINT per-worker shards at (re)start
        # — elastic restarts re-split over the surviving worker count.
        self.datasets = datasets
        self.controller_state = ControllerState.INITIALIZING
        self.state_history: List[str] = [ControllerState.INITIALIZING]
        # One entry per elastic recovery: cause, next world size, planned
        # backoff, budget line, and (once the next attempt reports) the
        # failure→first-report recovery time.
        self.recovery_log: List[Dict[str, Any]] = []
        self._failure_ts: Optional[float] = None
        self._attempt_reported = False
        # Training-path observability state: one goodput entry per
        # attempt ({attempt, world, wall_s, components, per_rank}),
        # currently-flagged straggler ranks, and the run trace ids.
        self.goodput_log: List[Dict[str, Any]] = []
        self.stragglers: set = set()
        self._trace_id = ""
        self._run_span = ""
        self._run_name = ""
        self._detector: Optional[goodput.StragglerDetector] = None
        self._pending_recovery: Optional[elastic.RecoveryTrace] = None
        self._ledger_prev: Dict[str, float] = {}
        self._last_ledgers: List[Dict[str, Any]] = []

    def _set_state(self, state: str) -> None:
        if state != self.controller_state:
            logger.info("train controller: %s -> %s",
                        self.controller_state, state)
            self.controller_state = state
            self.state_history.append(state)

    def _elastic_worker_target(self, explicit: Optional[int] = None) -> int:
        """How many workers to (re)start with: an explicit resize target
        when one is latched, else the full ask when rigid, or whatever the
        cluster can currently supply down to ``min_workers`` when elastic
        (reference: Train v2 elastic resizing on recovery)."""
        sc = self.scaling_config
        want = max(int(explicit), 1) if explicit else sc.num_workers
        floor = sc.min_workers if sc.min_workers is not None else want
        floor = min(floor, want)
        if floor >= want:
            return want
        try:
            avail = ray_tpu.available_resources()
        except Exception:  # noqa: BLE001
            return want
        # Feasibility is the min over EVERY resource the worker asks for
        # (a CPU-only estimate would still deadlock TPU-constrained jobs).
        feasible = want
        for key, per in sc.worker_resources().items():
            if per > 0:
                feasible = min(feasible,
                               int(avail.get(key, 0.0) // per))
        return max(min(want, feasible), floor)

    def fit(self) -> Result:
        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.util import tracing

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        rc = self.run_config
        storage_path = rc.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        name = rc.name or f"JaxTrainer_{int(time.time())}"
        # One trace per run: every attempt, step window, and elastic
        # recovery parents (transitively) to this root span, all
        # carrying run=<name> so `ray-tpu trace train <name>` finds it.
        self._run_name = name
        self._trace_id = tracing.gen_id()
        self._run_span = tracing.gen_id()
        run_t0_wall = time.time()
        storage = None
        if "://" in storage_path:
            # Cloud-fs persistence (reference StorageContext): the run's
            # working dir stays local; checkpoints mirror to the pyarrow
            # filesystem behind the URI.
            from ray_tpu.train.storage import StorageContext

            storage = StorageContext(storage_path, name)
            exp_dir = os.path.join(tempfile.gettempdir(),
                                   "ray_tpu_results", name)
        else:
            exp_dir = os.path.join(storage_path, name)
        os.makedirs(exp_dir, exist_ok=True)

        ckpt_cfg: CheckpointConfig = rc.checkpoint_config
        manager = CheckpointManager(
            os.path.join(exp_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
            async_write=ckpt_cfg.async_write,
            storage=storage,
        )

        failure_cfg: FailureConfig = rc.failure_config
        # Per-cause budgets (elastic.py taxonomy). Preemptions/resizes are
        # routine on TPU pods, not failures: each gets its own budget
        # instead of consuming max_failures; infrastructure loss gets the
        # restart budget.
        budgets = {
            elastic.USER: failure_cfg.max_failures,
            elastic.WORKER_LOST: _env_int("RAY_TPU_MAX_RESTARTS", 16),
            elastic.HANG: _env_int("RAY_TPU_MAX_RESTARTS", 16),
            elastic.PREEMPTION: _env_int("RAY_TPU_MAX_PREEMPTIONS", 64),
            elastic.RESIZE: _env_int("RAY_TPU_MAX_RESIZES", 64),
        }
        counts = {k: 0 for k in budgets}
        # worker_lost and hang share the restart budget.
        shared_restart = (elastic.WORKER_LOST, elastic.HANG)
        backoff_base = _env_float("RAY_TPU_RESTART_BACKOFF_S", 1.0)
        backoff_cap = _env_float("RAY_TPU_RESTART_BACKOFF_MAX_S", 30.0)
        backoff_streak = 0

        restore: Optional[Checkpoint] = self.resume_from_checkpoint
        latest_metrics: Optional[Dict[str, Any]] = None
        history: List[Dict[str, Any]] = []
        error: Optional[BaseException] = None
        resize_target: Optional[int] = None
        mtags = {"trainer": type(self).__name__}
        guard = elastic.ResizeGuard()
        attempt_idx = 0

        try:
            while True:
                self._set_state(ControllerState.SCHEDULING)
                resize_target = guard.target or resize_target
                target = self._elastic_worker_target(resize_target)
                mdefs.TRAIN_WORLD_SIZE.set(float(target), tags=mtags)
                scaling = self.scaling_config
                if target != scaling.num_workers:
                    import dataclasses as _dc

                    logger.warning(
                        "elastic training: starting with %d/%d workers "
                        "(min_workers=%s)", target, scaling.num_workers,
                        scaling.min_workers)
                    scaling = _dc.replace(scaling, num_workers=target)
                executor = BackendExecutor(scaling, self.backend)
                self._attempt_reported = False
                attempt_idx += 1
                attempt_span = tracing.gen_id()
                attempt_t0_wall = time.time()
                # Fresh per-attempt observability state: a new straggler
                # detector at this world size, cleared flags (a restart
                # re-forms the mesh — old rank identities are void), and
                # a zeroed goodput-delta cursor.
                self._detector = goodput.StragglerDetector(
                    scaling.num_workers)
                for r in sorted(self.stragglers):
                    mdefs.TRAIN_STRAGGLER.set(
                        0.0, tags={**mtags, "rank": str(r)})
                    self._publish_straggler(r, None)
                self.stragglers.clear()
                self._ledger_prev = {}
                self._last_ledgers = []
                try:
                    if self._pending_recovery is not None:
                        # Worker re-acquisition + backend on_start (the
                        # jax.distributed mesh re-formation) is one
                        # recovery phase of the trace.
                        with self._pending_recovery.timed_phase(
                                "reacquire"):
                            executor.start()
                    else:
                        executor.start()
                    # Clear the ask this attempt serves — at its exact
                    # value, even when capacity only allowed a smaller
                    # world (an unsatisfiable ask must not re-trigger a
                    # zero-backoff resize loop; the periodic grow probe
                    # finishes the job when capacity appears). A newer
                    # ask that raced in stays latched.
                    guard.clear_target(resize_target
                                       if resize_target is not None
                                       else target)
                    # The mesh is formed at this world size (executor
                    # start = worker acquisition + backend on_start):
                    # mirror it for the chip-pool arbiter's handoff
                    # confirmation.
                    self._publish_world(scaling.num_workers, attempt_idx)
                    worker_datasets = None
                    if self.datasets:
                        worker_datasets = [
                            {} for _ in range(scaling.num_workers)]
                        for ds_name, ds in self.datasets.items():
                            shards = ds.streaming_split(
                                scaling.num_workers, name=ds_name)
                            for rank, it in enumerate(shards):
                                worker_datasets[rank][ds_name] = it
                    run_refs = executor.start_training(
                        self.train_loop, self.train_loop_config,
                        restore.path if restore else None, run_dir=exp_dir,
                        datasets=worker_datasets)
                    self._set_state(ControllerState.RUNNING)
                    self._drive(executor, run_refs, manager, history,
                                guard, scaling.num_workers, resize_target,
                                attempt_span)
                    latest_metrics = (history[-1]["metrics"]
                                      if history else None)
                    error = None
                    executor.shutdown()
                    self._record_goodput(attempt_idx, scaling.num_workers)
                    self._emit_attempt_span(
                        attempt_span, attempt_t0_wall, attempt=attempt_idx,
                        world=scaling.num_workers, outcome="finished")
                    self._set_state(ControllerState.FINISHED)
                    break
                except BaseException as e:  # noqa: BLE001 — classified below
                    # Detection stamp BEFORE teardown: recovery time is
                    # documented as covering group teardown, and the
                    # trace's teardown phase must live inside it.
                    t_detect = time.monotonic()
                    detect_wall = time.time()
                    executor.shutdown()
                    teardown_s = time.monotonic() - t_detect
                    if isinstance(e, (KeyboardInterrupt, SystemExit)):
                        raise
                    cause = elastic.classify_failure(e)
                    # A graceful drain raced a resize ask: workers that
                    # preempt-out while a world-target is latched are the
                    # resize happening, not a preemption.
                    if cause == elastic.PREEMPTION and \
                            guard.target is not None:
                        cause = elastic.RESIZE
                    if isinstance(e, elastic.ResizeRequested):
                        resize_target = e.world_target
                    self._record_goodput(attempt_idx, scaling.num_workers)
                    self._emit_attempt_span(
                        attempt_span, attempt_t0_wall, attempt=attempt_idx,
                        world=scaling.num_workers, outcome=cause)
                    if self._attempt_reported:
                        backoff_streak = 0
                    if cause == elastic.FATAL:
                        error = e
                        latest_metrics = (history[-1]["metrics"]
                                          if history else None)
                        self._set_state(ControllerState.ERRORED)
                        break
                    counts[cause] += 1
                    if cause in shared_restart:
                        used = sum(counts[k] for k in shared_restart)
                        budget = budgets[elastic.WORKER_LOST]
                    else:
                        used = counts[cause]
                        budget = budgets[cause]
                    recoverable = budget < 0 or used <= budget
                    if not recoverable:
                        error = e
                        latest_metrics = (history[-1]["metrics"]
                                          if history else None)
                        self._set_state(ControllerState.ERRORED)
                        break
                    self._set_state(ControllerState.RESTARTING)
                    mdefs.TRAIN_RESTARTS.inc(tags={**mtags,
                                                   "cause": cause})
                    try:
                        # Restore only from fully-persisted dirs; a failed
                        # async persist drops its entry and must not abort
                        # the recovery it exists to serve.
                        manager.flush()
                    except Exception as persist_err:  # noqa: BLE001
                        logger.warning("checkpoint persist failed (%s); "
                                       "restoring from the previous one",
                                       persist_err)
                    restore = manager.latest or restore
                    if cause in (elastic.PREEMPTION, elastic.RESIZE):
                        backoff = 0.0  # the host is going / capacity moved
                    else:
                        backoff = min(
                            backoff_base * math.pow(2, backoff_streak),
                            backoff_cap)
                        backoff_streak += 1
                    # Recovery clock starts at DETECTION (so teardown is
                    # inside it, as the recovery metric documents); the
                    # trace phases accumulated here close into one
                    # train.recovery span tree at the restarted
                    # attempt's first report (_drive). A recovery still
                    # pending here means the RESTARTED attempt died
                    # before reporting: close its trace as failed (span
                    # length = detect A -> detect B) instead of
                    # silently dropping it.
                    if self._pending_recovery is not None and \
                            self._failure_ts is not None:
                        self._pending_recovery.close(
                            t_detect - self._failure_ts,
                            outcome="failed")
                        self._pending_recovery = None
                    self._failure_ts = t_detect
                    # Tie the recovery to the flight event that killed
                    # the attempt: a PreemptedError carries the notice
                    # (whose notice_id IS its event id), a chaos kill
                    # carries the injection's event id.
                    cause_event = ""
                    notice = getattr(e, "notice", None)
                    if isinstance(notice, dict):
                        cause_event = str(notice.get("notice_id", ""))
                    if not cause_event:
                        cause_event = str(getattr(e, "event_id", ""))
                    rec = elastic.RecoveryTrace(
                        self._trace_id, self._run_span, self._run_name,
                        cause, attempt_idx + 1, cause_event=cause_event)
                    rec.t0_wall = detect_wall
                    rec.phase("teardown", teardown_s)
                    self.recovery_log.append({
                        "cause": cause, "error": str(e)[:200],
                        "rank": getattr(e, "failed_rank", None),
                        "backoff_s": backoff,
                        "budget": f"{used}/{budget}",
                        "world_target": resize_target, "ts": time.time()})
                    logger.warning(
                        "training attempt ended (%s: %s); restarting from "
                        "%s in %.2fs (budget %d/%s)", cause, e,
                        restore.path if restore else
                        "the newest committed manifest", backoff, used,
                        budget)
                    if backoff:
                        time.sleep(backoff)
                        rec.phase("backoff", backoff)
                    self._pending_recovery = rec
        finally:
            guard.close()
            # The run is over: the arbiter must not keep confirming
            # against a dead run's world record.
            self._publish_world(0, attempt_idx, ended=True)
            # The straggler GAUGE must not report an
            # active straggler for a training run that no longer exists.
            # The KV record stays (ts-stamped, marked ended) as the
            # post-mortem surface, like `JaxTrainer.stragglers`.
            for r in sorted(self.stragglers):
                mdefs.TRAIN_STRAGGLER.set(0.0,
                                          tags={**mtags, "rank": str(r)})
                det = self._detector
                info = (det.flagged.get(r, {}) if det else {})
                self._publish_straggler(
                    r, {**info, "run": self._run_name,
                        "run_ended": True})
            if tracing.enabled():
                tracing.emit_span(
                    "train.run", trace_id=self._trace_id,
                    ts=run_t0_wall, dur=time.time() - run_t0_wall,
                    span_id=self._run_span, kind="train",
                    run=self._run_name, attempts=attempt_idx,
                    outcome=self.controller_state)

        try:
            manager.close()
        except Exception as persist_err:  # noqa: BLE001
            logger.warning("final checkpoint persist failed: %s",
                           persist_err)
        return Result(
            metrics=latest_metrics,
            checkpoint=manager.best,
            path=exp_dir,
            error=error,
            metrics_history=history,
        )

    # ------------------------------------- training-path observability
    def _emit_attempt_span(self, span_id: str, t0_wall: float, *,
                           attempt: int, world: int, outcome: str) -> None:
        from ray_tpu.util import tracing

        if not tracing.enabled():
            return
        tracing.emit_span(
            "train.attempt", trace_id=self._trace_id, ts=t0_wall,
            dur=time.time() - t0_wall, span_id=span_id,
            parent_span_id=self._run_span, kind="train",
            run=self._run_name, attempt=attempt, world=world,
            outcome=outcome)

    def _record_goodput(self, attempt: int, world: int) -> None:
        """Freeze the attempt's goodput entry from the last ledger
        snapshots the poll loop saw (rank 0 is the headline; per-rank
        snapshots ride along)."""
        if not self._last_ledgers:
            return
        lead = next((led for led in self._last_ledgers
                     if led.get("rank") == 0), self._last_ledgers[0])
        self.goodput_log.append({
            "attempt": attempt, "world": world,
            "wall_s": lead["wall_s"],
            "components": dict(lead["components"]),
            "per_rank": list(self._last_ledgers)})

    def goodput_summary(self) -> Dict[str, Any]:
        """Run-level goodput rollup: per-component seconds summed over
        every attempt's ledger (exact per-attempt partitions), plus the
        controller-side recovery total (detection→first report; it
        overlaps each young attempt's restore/first-step wall, so it is
        reported beside the components, not inside them)."""
        comps: Dict[str, float] = {}
        wall = 0.0
        for e in self.goodput_log:
            wall += e["wall_s"]
            for c, v in e["components"].items():
                comps[c] = comps.get(c, 0.0) + v
        rec = sum(r.get("recovery_s", 0.0) for r in self.recovery_log)
        return {
            "attempts": len(self.goodput_log),
            "wall_s": wall,
            "components": comps,
            "controller_recovery_s": rec,
            "fractions": ({c: v / wall for c, v in comps.items()}
                          if wall > 0 else {}),
        }

    def _publish_world(self, world: int, attempt: int,
                       ended: bool = False) -> None:
        """Mirror the attempt's confirmed world size into the GCS
        ``__train__`` KV (``world/<run>``) — the chip-pool arbiter reads
        this to confirm a mesh re-formed at a leased world size before
        committing the handoff. Best-effort like the straggler mirror."""
        try:
            import json

            from ray_tpu.experimental import internal_kv as kv

            rec = {"world": int(world), "attempt": int(attempt),
                   "ts": time.time()}
            if ended:
                rec["run_ended"] = True
            kv.internal_kv_put(f"world/{self._run_name}",
                               json.dumps(rec).encode(),
                               overwrite=True, namespace=TRAIN_KV_NS)
        except Exception:  # noqa: BLE001 — KV mirror is best-effort
            pass

    def _publish_straggler(self, rank: int,
                           info: Optional[Dict[str, Any]]) -> None:
        """Mirror a straggler flag into the GCS ``__train__`` KV
        (``straggler/<run>/<rank>``); ``info=None`` clears it.
        Best-effort like the worker heartbeat mirror."""
        try:
            import json

            from ray_tpu.experimental import internal_kv as kv

            key = f"straggler/{self._run_name}/{rank:05d}"
            if info is None:
                kv.internal_kv_del(key, namespace=TRAIN_KV_NS)
            else:
                kv.internal_kv_put(key, json.dumps(info).encode(),
                                   overwrite=True, namespace=TRAIN_KV_NS)
        except Exception:  # noqa: BLE001 — KV mirror is best-effort
            pass

    def _handle_window(self, win: Dict[str, Any], attempt_span: str,
                       world: int, mtags: Dict[str, str]) -> None:
        """One scored step window: emit its trace span and apply
        straggler flag transitions (gauge + KV + controller log)."""
        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.util import tracing

        if tracing.enabled() and win.get("start_ts") is not None:
            tracing.emit_span(
                "train.step_window", trace_id=self._trace_id,
                ts=win["start_ts"],
                dur=max(win["end_ts"] - win["start_ts"], 0.0),
                parent_span_id=attempt_span, kind="train",
                run=self._run_name, window=win["window"], world=world,
                median_s=round(win["median_s"], 6),
                max_skew=round(win["max_skew"], 3),
                stragglers=",".join(map(str, win["flagged"])))
        det = self._detector
        for r in win["newly_flagged"]:
            self.stragglers.add(r)
            info = det.flagged.get(r, {}) if det else {}
            mdefs.TRAIN_STRAGGLER.set(1.0, tags={**mtags,
                                                 "rank": str(r)})
            self._publish_straggler(r, {**info, "run": self._run_name})
            logger.warning(
                "straggler: rank %d mean step %.4fs is %.1fx the window "
                "median %.4fs for %d consecutive windows (run %s, "
                "window %d)", r, info.get("mean_s", 0.0),
                info.get("skew", 0.0), win["median_s"],
                info.get("streak", 0), self._run_name, win["window"])
        for r in win["cleared"]:
            self.stragglers.discard(r)
            mdefs.TRAIN_STRAGGLER.set(0.0, tags={**mtags,
                                                 "rank": str(r)})
            self._publish_straggler(r, None)
            logger.info("straggler cleared: rank %d back under the "
                        "skew threshold (run %s, window %d)",
                        r, self._run_name, win["window"])

    def _feed_step_timings(self, polls: List[Dict[str, Any]],
                           mtags: Dict[str, str], attempt_span: str,
                           current_world: int) -> None:
        """Per-rank step timelines off one poll round: rank histogram +
        straggler detector, then act on windows that completed. Shared
        by the live poll loop and the end-of-run drain (windows that
        complete only in the final reports must still score, or a rank
        that recovered at the end would finish the run flagged)."""
        from ray_tpu._private import metrics_defs as mdefs

        completed = []
        for rank, p in enumerate(polls):
            for r in p["reports"]:
                t = r.get("step_timing")
                if not t or self._detector is None:
                    continue
                if t.get("first"):
                    # Session-start → first report: setup/compile/
                    # restore, not a step — would pollute window means.
                    continue
                mdefs.TRAIN_RANK_STEP_SECONDS.observe(
                    t["dur"], tags={**mtags, "rank": str(rank)})
                completed += self._detector.observe(
                    rank, t["step"], t["dur"], ts=t.get("ts"))
        for win in completed:
            self._handle_window(win, attempt_span, current_world, mtags)

    def _account_goodput(self, polls: List[Dict[str, Any]],
                         mtags: Dict[str, str]) -> None:
        """Difference rank-0's ledger snapshot into the goodput counter
        family and refresh the fraction gauges. The counters are
        monotone (a shrinking step residual between two snapshots is
        skipped), so they approximate the exact per-attempt partition
        kept in ``goodput_log``."""
        from ray_tpu._private import metrics_defs as mdefs

        ledgers = [p.get("ledger") for p in polls]
        self._last_ledgers = [dict(led, rank=rank)
                              for rank, led in enumerate(ledgers) if led]
        lead = ledgers[0] if ledgers else None
        if not lead:
            return
        wall = max(lead["wall_s"], 1e-9)
        for comp, val in lead["components"].items():
            delta = val - self._ledger_prev.get(comp, 0.0)
            if delta > 0:
                mdefs.TRAIN_GOODPUT_SECONDS.inc(
                    delta, tags={**mtags, "component": comp})
                self._ledger_prev[comp] = val
            mdefs.TRAIN_GOODPUT_FRACTION.set(
                val / wall, tags={**mtags, "component": comp})

    # ------------------------------------------------------------------
    def _watchdog_s(self) -> float:
        w = self.run_config.failure_config.watchdog_s
        if w is None:
            w = _env_float("RAY_TPU_STEP_WATCHDOG_S", 0.0)
        return float(w)

    def _nan_fatal_reports(self) -> int:
        n = self.run_config.failure_config.nan_fatal_reports
        if n is None:
            n = _env_int("RAY_TPU_NAN_FATAL_REPORTS", 0)
        return int(n)

    def _drive(self, executor: BackendExecutor, run_refs,
               manager: CheckpointManager, history: List[Dict[str, Any]],
               guard: elastic.ResizeGuard, current_world: int,
               explicit_world: Optional[int] = None,
               attempt_span: str = ""):
        """Poll session queues until every worker's run() completes.

        Also the detection loop: the per-step watchdog, the fatal-NaN
        guard, and resize triggers (explicit world-target hints; periodic
        grow-back feasibility probes) all run off this poll cadence —
        ``executor.poll()`` itself raises on actor death and heartbeat
        lapses."""
        from ray_tpu._private import metrics_defs as mdefs

        mtags = {"trainer": type(self).__name__}
        last_report_ts = 0.0
        watchdog_s = self._watchdog_s()
        nan_fatal = self._nan_fatal_reports()
        nan_streak = 0
        grow_check_s = _env_float("RAY_TPU_GROW_CHECK_S", 30.0)
        started = time.monotonic()
        last_progress = started
        next_grow_check = started + grow_check_s
        first_report_seen = False

        def observe_round(metrics, nreports):
            """Per-step observability: report cadence is the step cadence
            (reference: workers report once per step), so the wall time
            since the previous poll round, split across the ``nreports``
            steps merged this round, is the per-step time — recording the
            raw inter-call gap would log ~0s for every buffered report
            when steps back up. A tokens_per_s metric key feeds the
            throughput gauge."""
            nonlocal last_report_ts
            now = time.monotonic()
            mdefs.TRAIN_REPORTS.inc(nreports, tags=mtags)
            if last_report_ts:
                per_step = (now - last_report_ts) / nreports
                for _ in range(nreports):
                    mdefs.TRAIN_STEP_SECONDS.observe(per_step, tags=mtags)
            last_report_ts = now
            tps = (metrics or {}).get("tokens_per_s")
            if isinstance(tps, (int, float)):
                mdefs.TRAIN_TOKENS_PER_S.set(float(tps), tags=mtags)

        while True:
            polls = executor.poll()
            # Per-rank step timelines: every report carries its step's
            # wall time; feed the rank histogram and the straggler
            # detector, then act on any windows that completed.
            self._feed_step_timings(polls, mtags, attempt_span,
                                    current_world)
            # Merge this round's reports: workers report at the same cadence;
            # rank 0's metrics win, any rank's checkpoint is persisted
            # (reference keeps rank-0 checkpoints by default).
            max_reports = max((len(p["reports"]) for p in polls), default=0)
            for i in range(max_reports):
                metrics = None
                ckpt_path = None
                for rank, p in enumerate(polls):
                    if i < len(p["reports"]):
                        r = p["reports"][i]
                        if metrics is None:
                            metrics = r["metrics"]
                        if ckpt_path is None and r.get("checkpoint_path"):
                            ckpt_path = r["checkpoint_path"]
                entry: Dict[str, Any] = {"metrics": metrics}
                if ckpt_path:
                    persisted = manager.register(
                        Checkpoint(ckpt_path), metrics or {})
                    entry["checkpoint"] = persisted
                history.append(entry)
                # Fatal-NaN guard: consecutive non-finite losses mean a
                # restart would replay the same divergence.
                loss = (metrics or {}).get("loss")
                if isinstance(loss, (int, float)):
                    if not math.isfinite(float(loss)):
                        nan_streak += 1
                        if nan_fatal and nan_streak >= nan_fatal:
                            raise exceptions.NaNLossError(
                                reports=nan_streak)
                    else:
                        nan_streak = 0
            if max_reports:
                observe_round(metrics, max_reports)
                self._account_goodput(polls, mtags)
                now = time.monotonic()
                last_progress = now
                self._attempt_reported = True
                if not first_report_seen:
                    first_report_seen = True
                    if self._failure_ts is not None:
                        recovery_s = now - self._failure_ts
                        mdefs.TRAIN_RECOVERY_SECONDS.observe(
                            recovery_s, tags=mtags)
                        # The goodput counter family gets only the
                        # INTER-session dead time (detection → the new
                        # session's start): the tail of the recovery
                        # (restore + first step) already flows in
                        # through the young attempt's own ledger, and
                        # the counters must not book it twice.
                        lead = polls[0].get("ledger") if polls else None
                        dead_s = recovery_s - (lead["wall_s"] if lead
                                               else 0.0)
                        if dead_s > 0:
                            mdefs.TRAIN_GOODPUT_SECONDS.inc(
                                dead_s,
                                tags={**mtags, "component": "recovery"})
                        if self.recovery_log:
                            self.recovery_log[-1]["recovery_s"] = \
                                recovery_s
                        if self._pending_recovery is not None:
                            # Same recovery_s closes the trace: the
                            # train.recovery span and the metric can
                            # never disagree.
                            self._pending_recovery.close(recovery_s)
                            self._pending_recovery = None
                        self._failure_ts = None
            # Per-step watchdog: a hung collective stalls every worker's
            # report stream while heartbeats keep flowing. Before the
            # first report the deadline is 10x (compile headroom).
            if watchdog_s > 0:
                deadline = watchdog_s if first_report_seen \
                    else watchdog_s * 10.0
                stalled = time.monotonic() - last_progress
                if stalled > deadline:
                    raise exceptions.WorkerHangError(
                        f"step watchdog: no report for {stalled:.1f}s "
                        f"(deadline {deadline:.1f}s)", kind="watchdog")
            # Resize triggers: explicit world-target hints win; otherwise
            # a periodic feasibility probe grows a shrunk group back when
            # capacity returns (the GCS capacity-grew pubsub hint makes
            # the probe immediate).
            wt = guard.target
            if wt is not None:
                if wt != current_world:
                    raise elastic.ResizeRequested(
                        wt, reason="world-target hint")
                # A no-op ask (already at this world) must unlatch, or a
                # later genuine preemption would be reclassified as a
                # resize by fit()'s latched-target check.
                guard.clear_target(wt)
            now = time.monotonic()
            if guard.take_grow_hint():
                next_grow_check = now
            if grow_check_s > 0 and now >= next_grow_check:
                next_grow_check = now + grow_check_s
                # Grow back toward the full ask when capacity returns —
                # but never undo an operator's explicit shrink: a world
                # size the operator asked for by name is not a
                # capacity-driven degradation.
                if current_world < self.scaling_config.num_workers and \
                        current_world != explicit_world:
                    feasible = self._elastic_worker_target(None)
                    if feasible > current_world:
                        raise elastic.ResizeRequested(
                            feasible, reason="capacity returned")

            done, _ = ray_tpu.wait(run_refs, num_returns=len(run_refs),
                                   timeout=0.02)
            if len(done) == len(run_refs):
                # Raises through to fit() on worker failure.
                ray_tpu.get(run_refs)
                # Final drain: reports AND step timings (windows that
                # complete only here must still score — a straggler
                # that recovered in the last windows gets its cleared
                # transition, not a stale flag).
                final = executor.poll()
                self._feed_step_timings(final, mtags, attempt_span,
                                        current_world)
                for rank, p in enumerate(final):
                    for r in p["reports"]:
                        entry = {"metrics": r["metrics"]}
                        if r.get("checkpoint_path"):
                            entry["checkpoint"] = manager.register(
                                Checkpoint(r["checkpoint_path"]),
                                r["metrics"] or {})
                        history.append(entry)
                # Closing ledger snapshots (wall frozen at session end)
                # become the attempt's goodput_log entry.
                self._account_goodput(final, mtags)
                # A world-target ask that landed while the final steps
                # were completing must NOT be silently dropped: re-form
                # at the asked world (the restarted attempt restores
                # past the last step and finishes immediately when no
                # work remains, but the ask is honored and the world
                # gauge/budget reflect it).
                wt = guard.target
                if wt is not None and wt != current_world:
                    raise elastic.ResizeRequested(
                        wt, reason="world-target hint")
                return
