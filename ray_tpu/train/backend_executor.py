"""Worker group + backend executor for distributed training.

Reference call stack (SURVEY.md §3.4): ``BackendExecutor.start``
(``train/_internal/backend_executor.py:142``) creates a placement group,
spawns N worker actors (``_internal/worker_group.py``), shares accelerator
visibility among colocated workers, assigns ranks, runs
``train_loop_per_worker`` and polls a session queue for results.

TPU-native differences:

* ``JaxBackend.on_start`` is where multi-host SPMD bootstrap happens
  (``jax.distributed.initialize`` with a coordinator chosen from worker 0 —
  the analog of the reference's MASTER_ADDR + ``dist.init_process_group``,
  ``train/torch/config.py:153``). In single-process runtimes it is a no-op.
* Accelerator visibility shares ``TPU_VISIBLE_CHIPS`` (the reference shares
  ``CUDA_VISIBLE_DEVICES``, ``backend_executor.py:278``).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train import session as session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig

logger = logging.getLogger(__name__)


class TrainWorker:
    """Actor hosting one training process (reference: ``RayTrainWorker``)."""

    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 local_world_size: int, env: Optional[Dict[str, str]] = None):
        self.rank = world_rank
        for k, v in (env or {}).items():
            os.environ[k] = v
        self._ctx = session_mod.TrainContext(
            world_rank, world_size, local_rank, local_world_size)
        self._session: Optional[session_mod._Session] = None
        self._lock = threading.Lock()

    def setup(self, env: Dict[str, str]):
        for k, v in env.items():
            os.environ[k] = v
        return True

    def node_ip(self) -> str:
        """Routable address of this worker's host — the coordinator must be
        reachable from every other host, so loopback is only the fallback."""
        import socket

        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect(("8.8.8.8", 80))  # no packet sent; routing only
                return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"

    def reserve_port(self) -> int:
        """Free port on this worker's host for the coordinator service."""
        import socket

        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    def init_jax_distributed(self, coordinator: str, num_processes: int):
        """Join the jax.distributed group (reference analog: MASTER_ADDR +
        ``dist.init_process_group``, ``train/torch/config.py:153``). Worker
        0 hosts the coordinator service; every process must call in before
        any jax computation runs in it."""
        import jax

        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=self.rank)
        except RuntimeError as e:
            if "already" not in str(e).lower():
                raise
        return jax.process_index()

    def run(self, fn: Callable, config: Optional[Dict[str, Any]],
            restore_checkpoint_path: Optional[str],
            run_dir: Optional[str] = None,
            dataset_shards: Optional[Dict[str, Any]] = None):
        """Run the user train loop to completion (blocking actor call)."""
        ckpt = (Checkpoint(restore_checkpoint_path)
                if restore_checkpoint_path else None)
        s = session_mod._Session(self._ctx, ckpt, run_dir=run_dir,
                                 dataset_shards=dataset_shards)
        with self._lock:
            self._session = s
        session_mod._set_session(s)
        try:
            s.result = fn(config) if config is not None else fn()
            return s.result
        finally:
            if s.checkpoint_plane is not None:
                # Join in-flight async saves so a committed manifest is
                # durable before the controller sees this worker finish.
                try:
                    s.checkpoint_plane.close()
                except Exception:  # noqa: BLE001 — loop outcome wins
                    logger.exception("checkpoint plane close failed")
            s.finished.set()
            session_mod._set_session(None)

    def poll(self) -> Dict[str, Any]:
        """Drain pending reports (runs concurrently with ``run``)."""
        with self._lock:
            s = self._session
        if s is None:
            return {"reports": [], "finished": False}
        reports = []
        while True:
            try:
                r = s.reports.get_nowait()
            except queue.Empty:
                break
            # Checkpoints cross the actor boundary as paths.
            if r.get("checkpoint") is not None:
                r = dict(r, checkpoint_path=r.pop("checkpoint").path)
            reports.append(r)
        return {"reports": reports, "finished": s.finished.is_set()}


class WorkerGroup:
    """Reference: ``train/_internal/worker_group.py``."""

    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling
        worker_cls = ray_tpu.remote(TrainWorker)
        n = scaling.num_workers
        self.workers = [
            worker_cls.options(
                num_cpus=scaling.worker_resources().get("CPU", 1),
                resources={k: v for k, v in scaling.worker_resources().items()
                           if k not in ("CPU", "GPU")},
                max_concurrency=2,  # run() + poll() concurrently
            ).remote(rank, n, rank, n)
            for rank in range(n)
        ]

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [getattr(w, method).remote(*args, **kwargs) for w in self.workers]
        )

    def execute_async(self, method: str, *args, **kwargs):
        return [getattr(w, method).remote(*args, **kwargs) for w in self.workers]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []


class JaxBackend:
    """Backend plugin (reference ABC: ``train/backend.py``)."""

    def on_start(self, worker_group: WorkerGroup, scaling: ScalingConfig):
        # Multi-host bootstrap: worker 0 is the jax.distributed coordinator.
        # In the in-process runtime all workers share one jax client, so the
        # only thing to share is TPU visibility (reference shares
        # CUDA_VISIBLE_DEVICES across colocated workers).
        env = {"RAY_TPU_TRAIN_WORLD_SIZE": str(scaling.num_workers)}
        worker_group.execute("setup", env)
        if scaling.jax_distributed and scaling.num_workers > 1:
            w0 = worker_group.workers[0]
            host = ray_tpu.get(w0.node_ip.remote())
            port = ray_tpu.get(w0.reserve_port.remote())
            coordinator = f"{host}:{port}"
            try:
                # Published for observability and late joiners (elastic
                # restarts re-read it) — the KV is the MASTER_ADDR channel.
                from ray_tpu._private import worker as _worker_mod
                from ray_tpu.protobuf import ray_tpu_pb2 as pb

                _worker_mod.global_worker().core.gcs.KvPut(pb.KvRequest(
                    ns="train", key=f"coordinator/{id(worker_group)}",
                    value=coordinator.encode(), overwrite=True))
            except Exception:  # noqa: BLE001 — local mode has no GCS
                pass
            ranks = worker_group.execute(
                "init_jax_distributed", coordinator, scaling.num_workers)
            logger.info("jax.distributed group formed: coordinator=%s "
                        "ranks=%s", coordinator, ranks)

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class BackendExecutor:
    """Reference: ``train/_internal/backend_executor.py:69``."""

    def __init__(self, scaling: ScalingConfig, backend: Optional[JaxBackend] = None):
        self.scaling = scaling
        self.backend = backend or JaxBackend()
        self.worker_group: Optional[WorkerGroup] = None

    def start(self):
        self.worker_group = WorkerGroup(self.scaling)
        self.backend.on_start(self.worker_group, self.scaling)

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]],
                       restore_checkpoint_path: Optional[str],
                       run_dir: Optional[str] = None,
                       datasets: Optional[List[Dict[str, Any]]] = None
                       ) -> List[Any]:
        """``datasets`` is PER-RANK: element ``i`` is rank i's
        ``{name: DataIterator}`` map of disjoint streaming_split shards
        (every other start_training arg is identical across ranks)."""
        assert self.worker_group is not None
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            shards = datasets[rank] if datasets else None
            refs.append(w.run.remote(train_fn, config,
                                     restore_checkpoint_path, run_dir,
                                     shards))
        return refs

    def poll(self) -> List[Dict[str, Any]]:
        assert self.worker_group is not None
        return self.worker_group.execute("poll")

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None
