"""Worker group + backend executor for distributed training.

Reference call stack (SURVEY.md §3.4): ``BackendExecutor.start``
(``train/_internal/backend_executor.py:142``) creates a placement group,
spawns N worker actors (``_internal/worker_group.py``), shares accelerator
visibility among colocated workers, assigns ranks, runs
``train_loop_per_worker`` and polls a session queue for results.

TPU-native differences:

* ``JaxBackend.on_start`` is where multi-host SPMD bootstrap happens
  (``jax.distributed.initialize`` with a coordinator chosen from worker 0 —
  the analog of the reference's MASTER_ADDR + ``dist.init_process_group``,
  ``train/torch/config.py:153``). In single-process runtimes it is a no-op.
* Accelerator visibility shares ``TPU_VISIBLE_CHIPS`` (the reference shares
  ``CUDA_VISIBLE_DEVICES``, ``backend_executor.py:278``).

Elastic failure detection (reference: Train v2 worker-group health checks
+ the GCS health-check manager): every ``poll()`` is also a liveness
probe. Three independent channels feed it:

1. **actor death** — a dead worker's poll raises ``ActorDiedError``
   (annotated with the failed rank);
2. **heartbeats** — each worker runs a heartbeat thread that stamps a
   timestamp returned by ``poll()`` AND pushes it through the GCS KV
   (``__train__`` namespace) so a controller can see lapses even when the
   actor channel is slow; a lapse past ``RAY_TPU_TRAIN_HEARTBEAT_TTL_S``
   raises ``WorkerHangError(kind="heartbeat")``;
3. **step progress** — ``progress_ts`` moves on every ``train.report``;
   the trainer's per-step watchdog turns a stall into
   ``WorkerHangError(kind="watchdog")`` (hung collective).

The chaos harness (``_private/chaos.py``) can kill a worker at a step
boundary, drop/delay heartbeats, or wedge a step — each detection path
above is exercised by a real injected fault in tests.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.train import session as session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig

logger = logging.getLogger(__name__)

# Namespace for worker heartbeat records pushed through the GCS KV.
TRAIN_KV_NS = "__train__"


def _hb_period_s() -> float:
    return float(os.environ.get("RAY_TPU_TRAIN_HEARTBEAT_S", 0.5))


def _hb_ttl_s() -> float:
    return float(os.environ.get("RAY_TPU_TRAIN_HEARTBEAT_TTL_S", 5.0))


def _teardown_join_s() -> float:
    return float(os.environ.get("RAY_TPU_TEARDOWN_JOIN_S", 5.0))


class TrainWorker:
    """Actor hosting one training process (reference: ``RayTrainWorker``)."""

    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 local_world_size: int, env: Optional[Dict[str, str]] = None,
                 group_id: str = ""):
        self.rank = world_rank
        self.group_id = group_id
        for k, v in (env or {}).items():
            os.environ[k] = v
        self._ctx = session_mod.TrainContext(
            world_rank, world_size, local_rank, local_world_size)
        self._session: Optional[session_mod._Session] = None
        self._lock = threading.Lock()
        self._hb_ts: Optional[float] = None

    def setup(self, env: Dict[str, str]):
        for k, v in env.items():
            os.environ[k] = v
        return True

    def node_ip(self) -> str:
        """Routable address of this worker's host — the coordinator must be
        reachable from every other host, so loopback is only the fallback."""
        import socket

        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect(("8.8.8.8", 80))  # no packet sent; routing only
                return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"

    def reserve_port(self) -> int:
        """Free port on this worker's host for the coordinator service."""
        import socket

        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    def init_jax_distributed(self, coordinator: str, num_processes: int,
                             timeout_s: Optional[float] = None):
        """Join the jax.distributed group (reference analog: MASTER_ADDR +
        ``dist.init_process_group``, ``train/torch/config.py:153``). Worker
        0 hosts the coordinator service; every process must call in before
        any jax computation runs in it."""
        import jax

        kwargs = {}
        if timeout_s is not None:
            # jax's initialization_timeout is in seconds; old jax
            # versions lack the kwarg entirely (TypeError → retry bare).
            kwargs["initialization_timeout"] = max(int(timeout_s), 1)
        try:
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=num_processes,
                    process_id=self.rank, **kwargs)
            except TypeError:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=num_processes,
                    process_id=self.rank)
        except RuntimeError as e:
            if "already" not in str(e).lower():
                raise
        return jax.process_index()

    # ------------------------------------------------------- liveness
    def _heartbeat_loop(self, s: "session_mod._Session") -> None:
        """Stamp liveness every period until the session ends. Beats are
        surfaced two ways: the ``_hb_ts`` field returned by ``poll()``
        (actor channel) and a GCS KV record (``__train__`` namespace) so
        lapses are visible cluster-wide even if polls stall. The chaos
        ``train_heartbeat`` site can drop or delay beats."""
        from ray_tpu._private import chaos

        period = _hb_period_s()
        kv = None
        try:
            from ray_tpu.experimental import internal_kv as kv
        except Exception:  # noqa: BLE001 — no runtime in this process
            kv = None
        key = f"hb/{self.group_id}/{self.rank:05d}"
        while not s.finished.wait(period):
            if s.stop.is_set():
                return
            d = chaos.inject("train_heartbeat", rank=self.rank) or {}
            if d.get("delay_s"):
                time.sleep(float(d["delay_s"]))
            if d.get("drop"):
                continue
            now = time.time()
            self._hb_ts = now
            if kv is not None:
                try:
                    import json

                    kv.internal_kv_put(
                        key, json.dumps({"ts": now, "rank": self.rank,
                                         "pid": os.getpid()}).encode(),
                        overwrite=True, namespace=TRAIN_KV_NS)
                except Exception:  # noqa: BLE001 — KV push is best-effort
                    kv = None  # stop retrying a dead channel this session

    def stop(self) -> bool:
        """Cooperative teardown: flag the running session to unwind at its
        next report (elastic restart/resize)."""
        with self._lock:
            s = self._session
        if s is not None:
            s.stop.set()
        return True

    def run(self, fn: Callable, config: Optional[Dict[str, Any]],
            restore_checkpoint_path: Optional[str],
            run_dir: Optional[str] = None,
            dataset_shards: Optional[Dict[str, Any]] = None):
        """Run the user train loop to completion (blocking actor call)."""
        from ray_tpu._private import chaos

        ckpt = (Checkpoint(restore_checkpoint_path)
                if restore_checkpoint_path else None)
        s = session_mod._Session(self._ctx, ckpt, run_dir=run_dir,
                                 dataset_shards=dataset_shards,
                                 group_id=self.group_id)
        with self._lock:
            self._session = s
        session_mod._set_session(s)
        self._hb_ts = time.time()
        threading.Thread(target=self._heartbeat_loop, args=(s,),
                         daemon=True,
                         name=f"train-hb-{self.rank}").start()
        try:
            s.result = fn(config) if config is not None else fn()
            return s.result
        finally:
            if s.checkpoint_plane is not None and not chaos.process_dying():
                # Join in-flight async saves so a committed manifest is
                # durable before the controller sees this worker finish.
                # Skipped when unwinding a chaos-injected kill: a dead
                # process would never have flushed either.
                try:
                    s.checkpoint_plane.close()
                except Exception:  # noqa: BLE001 — loop outcome wins
                    logger.exception("checkpoint plane close failed")
            s.ledger.close()  # freeze the attempt's goodput wall clock
            s.finished.set()
            session_mod._set_session(None)

    def poll(self) -> Dict[str, Any]:
        """Drain pending reports (runs concurrently with ``run``)."""
        with self._lock:
            s = self._session
        if s is None:
            return {"reports": [], "finished": False}
        reports = []
        while True:
            try:
                r = s.reports.get_nowait()
            except queue.Empty:
                break
            # Checkpoints cross the actor boundary as paths.
            if r.get("checkpoint") is not None:
                r = dict(r, checkpoint_path=r.pop("checkpoint").path)
            reports.append(r)
        return {"reports": reports, "finished": s.finished.is_set(),
                "heartbeat_ts": self._hb_ts,
                "progress_ts": s.progress_ts, "last_step": s.last_step,
                # Goodput ledger snapshot (components sum to wall_s):
                # the controller differences consecutive snapshots into
                # ray_tpu_train_goodput_seconds_total{component}.
                "ledger": s.ledger.snapshot()}


class WorkerGroup:
    """Reference: ``train/_internal/worker_group.py``."""

    def __init__(self, scaling: ScalingConfig, group_id: str = ""):
        self.scaling = scaling
        self.group_id = group_id or uuid.uuid4().hex[:8]
        worker_cls = ray_tpu.remote(TrainWorker)
        n = scaling.num_workers
        self.workers = [
            worker_cls.options(
                num_cpus=scaling.worker_resources().get("CPU", 1),
                resources={k: v for k, v in scaling.worker_resources().items()
                           if k not in ("CPU", "GPU")},
                max_concurrency=3,  # run() + poll()/stop() concurrently
            ).remote(rank, n, rank, n, group_id=self.group_id)
            for rank in range(n)
        ]

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [getattr(w, method).remote(*args, **kwargs) for w in self.workers]
        )

    def execute_per_rank(self, method: str, *args, **kwargs) -> List[Any]:
        """Like :meth:`execute`, but a failure is attributed: raises the
        first failing rank's exception with ``failed_rank`` set on it.
        The happy path stays ONE batched get (this runs ~50Hz under the
        controller's poll loop); per-ref resolution only happens after
        the batch failed, when the refs are already local."""
        refs = [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]
        try:
            return ray_tpu.get(refs)
        except BaseException:  # noqa: BLE001 — attributed below
            pass
        out = []
        for rank, ref in enumerate(refs):
            try:
                out.append(ray_tpu.get(ref))
            except BaseException as e:  # noqa: BLE001 — annotate + re-raise
                try:
                    e.failed_rank = rank
                except Exception:  # noqa: BLE001 — frozen exception type
                    pass
                raise
        return out

    def execute_async(self, method: str, *args, **kwargs):
        return [getattr(w, method).remote(*args, **kwargs) for w in self.workers]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []


class JaxBackend:
    """Backend plugin (reference ABC: ``train/backend.py``)."""

    # Coordinator bootstrap retries: a stale/raced port rebinds to a fresh
    # one with exponential backoff before the environment is declared
    # unable to form a jax.distributed group.
    COORD_ATTEMPTS = 3
    COORD_BACKOFF_S = 0.5

    def on_start(self, worker_group: WorkerGroup, scaling: ScalingConfig):
        # Multi-host bootstrap: worker 0 is the jax.distributed coordinator.
        # In the in-process runtime all workers share one jax client, so the
        # only thing to share is TPU visibility (reference shares
        # CUDA_VISIBLE_DEVICES across colocated workers).
        env = {"RAY_TPU_TRAIN_WORLD_SIZE": str(scaling.num_workers)}
        worker_group.execute("setup", env)
        if scaling.jax_distributed and scaling.num_workers > 1:
            self._bootstrap_jax_distributed(worker_group, scaling)

    def _bootstrap_jax_distributed(self, worker_group: WorkerGroup,
                                   scaling: ScalingConfig) -> None:
        attempts = int(os.environ.get("RAY_TPU_JAX_COORD_ATTEMPTS",
                                      self.COORD_ATTEMPTS))
        w0 = worker_group.workers[0]
        host = ray_tpu.get(w0.node_ip.remote())
        last_err: Optional[BaseException] = None
        for attempt in range(attempts):
            # Fresh port every attempt: the dominant transient failure is
            # a coordinator port that raced another bind or lingers in
            # TIME_WAIT from a previous (failed) group.
            port = ray_tpu.get(w0.reserve_port.remote())
            coordinator = f"{host}:{port}"
            try:
                # Published for observability and late joiners (elastic
                # restarts re-read it) — the KV is the MASTER_ADDR channel.
                from ray_tpu._private import worker as _worker_mod
                from ray_tpu.protobuf import ray_tpu_pb2 as pb

                _worker_mod.global_worker().core.gcs.KvPut(pb.KvRequest(
                    ns="train", key=f"coordinator/{id(worker_group)}",
                    value=coordinator.encode(), overwrite=True))
            except Exception:  # noqa: BLE001 — local mode has no GCS
                pass
            try:
                # Bounded join: without a timeout a coordinator that never
                # comes up (sandboxed networking, firewalled port) hangs
                # the whole bootstrap instead of reaching the retry path.
                timeout_s = float(os.environ.get(
                    "RAY_TPU_JAX_COORD_TIMEOUT_S", 60.0))
                ranks = worker_group.execute(
                    "init_jax_distributed", coordinator,
                    scaling.num_workers, timeout_s)
                logger.info("jax.distributed group formed: coordinator=%s "
                            "ranks=%s", coordinator, ranks)
                return
            except Exception as e:  # noqa: BLE001 — bind/timeout/raced port
                last_err = e
                backoff = self.COORD_BACKOFF_S * (2 ** attempt)
                logger.warning(
                    "jax.distributed bootstrap attempt %d/%d failed on "
                    "%s (%s); rebinding coordinator port and retrying "
                    "in %.1fs", attempt + 1, attempts, coordinator, e,
                    backoff)
                time.sleep(backoff)
        raise exceptions.JaxDistributedBootstrapError(
            f"could not form a jax.distributed group after {attempts} "
            f"coordinator rebind attempts: {last_err}")

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class BackendExecutor:
    """Reference: ``train/_internal/backend_executor.py:69``."""

    def __init__(self, scaling: ScalingConfig, backend: Optional[JaxBackend] = None):
        self.scaling = scaling
        self.backend = backend or JaxBackend()
        self.worker_group: Optional[WorkerGroup] = None
        self._training_started_at: Optional[float] = None
        # rank -> (newest heartbeat stamp observed, controller-monotonic
        # time it changed) — the basis for skew-proof lapse detection.
        self._hb_seen: Dict[int, Tuple[float, float]] = {}

    @property
    def group_id(self) -> str:
        return self.worker_group.group_id if self.worker_group else ""

    def start(self):
        self.worker_group = WorkerGroup(self.scaling)
        self.backend.on_start(self.worker_group, self.scaling)

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]],
                       restore_checkpoint_path: Optional[str],
                       run_dir: Optional[str] = None,
                       datasets: Optional[List[Dict[str, Any]]] = None
                       ) -> List[Any]:
        """``datasets`` is PER-RANK: element ``i`` is rank i's
        ``{name: DataIterator}`` map of disjoint streaming_split shards
        (every other start_training arg is identical across ranks)."""
        assert self.worker_group is not None
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            shards = datasets[rank] if datasets else None
            refs.append(w.run.remote(train_fn, config,
                                     restore_checkpoint_path, run_dir,
                                     shards))
        self._hb_seen.clear()
        self._training_started_at = time.monotonic()
        return refs

    def poll(self) -> List[Dict[str, Any]]:
        """Drain worker reports AND probe liveness: raises
        ``ActorDiedError`` (with ``failed_rank``) for a dead worker, and
        ``WorkerHangError(kind="heartbeat")`` when a live-looking worker's
        heartbeats lapsed past ``RAY_TPU_TRAIN_HEARTBEAT_TTL_S``."""
        assert self.worker_group is not None
        polls = self.worker_group.execute_per_rank("poll")
        self._check_heartbeats(polls)
        return polls

    def _check_heartbeats(self, polls: List[Dict[str, Any]]) -> None:
        """Staleness is measured CONTROLLER-side: a rank lapses when the
        heartbeat stamp it reports stops *changing* for longer than the
        TTL on the controller's monotonic clock — never by differencing
        two hosts' wall clocks, which would declare every healthy worker
        hung under cross-host clock skew greater than the TTL."""
        ttl = _hb_ttl_s()
        if ttl <= 0 or self._training_started_at is None:
            return
        mono = time.monotonic()
        for rank, p in enumerate(polls):
            if p.get("finished") or "heartbeat_ts" not in p:
                continue
            hb = float(p.get("heartbeat_ts") or 0.0)
            seen = self._hb_seen.get(rank)
            if seen is None or hb > seen[0]:
                self._hb_seen[rank] = (hb, mono)
                continue
            if mono - seen[1] > ttl:
                # Second opinion from the GCS KV mirror before declaring
                # a lapse (the KV may be ahead when the actor path is
                # backed up) — only probed once the actor stamp is stale,
                # so the common case costs no KV round-trip.
                kv_hb = self._kv_heartbeat(rank)
                if kv_hb > seen[0]:
                    self._hb_seen[rank] = (kv_hb, mono)
                    continue
                raise exceptions.WorkerHangError(
                    f"rank {rank} heartbeats stalled "
                    f"{mono - seen[1]:.1f}s (TTL {ttl:.1f}s)",
                    rank=rank, kind="heartbeat")

    def _kv_heartbeat(self, rank: int) -> float:
        try:
            import json

            from ray_tpu.experimental import internal_kv as kv

            raw = kv.internal_kv_get(
                f"hb/{self.group_id}/{rank:05d}", namespace=TRAIN_KV_NS)
            return float(json.loads(raw)["ts"]) if raw else 0.0
        except Exception:  # noqa: BLE001 — KV probe is best-effort
            return 0.0

    def shutdown(self):
        if self.worker_group is not None:
            group_id = self.worker_group.group_id
            try:
                self.backend.on_shutdown(self.worker_group)
            except Exception:  # noqa: BLE001 — teardown must proceed
                logger.exception("backend on_shutdown failed")
            # Cooperative stop BEFORE the kill: in the in-process runtime
            # a killed actor's run() thread survives the kill, so flag its
            # session (shared memory) and, after the kill, wait for the
            # loop to unwind — zombie steps must not race the next
            # attempt's checkpoint stream.
            stopped = session_mod.stop_local_sessions(group_id)
            self.worker_group.shutdown()
            if stopped:
                session_mod.join_local_sessions(group_id,
                                                _teardown_join_s())
            self._drop_heartbeat_records(group_id)
            self.worker_group = None
            self._training_started_at = None
            self._hb_seen.clear()

    @staticmethod
    def _drop_heartbeat_records(group_id: str) -> None:
        """GC this generation's ``hb/<group_id>/*`` KV records — every
        elastic restart mints a fresh group_id, so without the sweep a
        long-lived cluster accumulates stale heartbeat keys forever."""
        try:
            from ray_tpu.experimental import internal_kv as kv

            for key in kv.internal_kv_list(f"hb/{group_id}/",
                                           namespace=TRAIN_KV_NS):
                kv.internal_kv_del(key, namespace=TRAIN_KV_NS)
        except Exception:  # noqa: BLE001 — KV gc is best-effort
            pass
