"""Per-worker training session: ``report`` / ``get_context`` / ``get_checkpoint``.

Reference: ``python/ray/train/_internal/session.py`` — the session is the
channel between the user's ``train_loop_per_worker`` and the controller:
metrics/checkpoints flow out through a queue polled by the BackendExecutor
(reference ``backend_executor.py:585``), and the restore checkpoint flows in.
"""

from __future__ import annotations

import contextvars
import queue
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.goodput import GoodputLedger


class TrainContext:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 local_world_size: int, node_rank: int = 0,
                 experiment_name: str = "", trial_name: str = ""):
        self._world_rank = world_rank
        self._world_size = world_size
        self._local_rank = local_rank
        self._local_world_size = local_world_size
        self._node_rank = node_rank
        self._experiment_name = experiment_name
        self._trial_name = trial_name

    def get_world_rank(self) -> int:
        return self._world_rank

    def get_world_size(self) -> int:
        return self._world_size

    def get_local_rank(self) -> int:
        return self._local_rank

    def get_local_world_size(self) -> int:
        return self._local_world_size

    def get_node_rank(self) -> int:
        return self._node_rank

    def get_experiment_name(self) -> str:
        return self._experiment_name

    def get_trial_name(self) -> str:
        return self._trial_name


class _Session:
    def __init__(self, context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 run_dir: Optional[str] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 group_id: str = ""):
        self.context = context
        self.restore_checkpoint = checkpoint
        self.run_dir = run_dir
        self.dataset_shards = dataset_shards or {}
        self.group_id = group_id  # worker-group generation (elastic fence)
        self.checkpoint_plane = None  # lazily built, one per session
        self.reports: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        # Cooperative teardown: the controller flips this when it re-forms
        # the group; report() raises WorkerStoppedError so in-process
        # zombie loops unwind instead of racing the next attempt.
        self.stop = threading.Event()
        # Liveness surfaced through TrainWorker.poll(): progress_ts moves
        # on every report, last_step mirrors the loop's step counter.
        self.progress_ts: float = time.monotonic()
        self.last_step: int = -1
        self.report_seq: int = 0
        # Training-path observability: one goodput ledger per attempt
        # (instrumented sites attribute through goodput.note_ambient);
        # each report also records the step's dispatch→report wall time
        # for the controller's per-window rank-skew / straggler scoring.
        self.ledger = GoodputLedger()
        self.error: Optional[BaseException] = None
        self.result: Any = None
        with _registry_lock:
            _active_sessions.add(self)


# Process-local registry of live sessions, keyed for stop/join by worker-
# group id. Only meaningful in the in-process runtime, where "killing" a
# worker actor cannot kill its (shared-process) thread: the executor flags
# the old generation's sessions to stop and waits for them to finish so
# zombie loops never race the next attempt's checkpoint stream. In
# cluster mode worker processes really die, and the controller-side
# registry is simply empty.
_registry_lock = threading.Lock()
_active_sessions: "weakref.WeakSet[_Session]" = weakref.WeakSet()


def _sessions_for_group(group_id: str) -> List[_Session]:
    with _registry_lock:
        return [s for s in _active_sessions
                if s.group_id == group_id and not s.finished.is_set()]


def stop_local_sessions(group_id: str) -> int:
    """Flag every unfinished in-process session of one worker group to
    stop at its next report. Returns how many were flagged."""
    sessions = _sessions_for_group(group_id)
    for s in sessions:
        s.stop.set()
    return len(sessions)


def join_local_sessions(group_id: str, timeout_s: float = 5.0) -> bool:
    """Wait for flagged sessions to unwind (bounded). False (with a
    warning) if a loop is still running — e.g. wedged inside a long
    sleep; when it wakes, its next ``plane.save`` or ``report`` raises
    ``WorkerStoppedError`` (the plane's save-time fence / the report
    stop check), so it cannot write into the next attempt's stream."""
    import logging

    deadline = time.monotonic() + timeout_s
    ok = True
    for s in _sessions_for_group(group_id):
        remaining = deadline - time.monotonic()
        if not s.finished.wait(max(remaining, 0.0)):
            ok = False
            logging.getLogger(__name__).warning(
                "train session (rank %d, group %s) still running %.1fs "
                "after teardown — a wedged step is being abandoned",
                s.context.get_world_rank(), group_id or "?", timeout_s)
    return ok


_session_var: contextvars.ContextVar[Optional[_Session]] = contextvars.ContextVar(
    "ray_tpu_train_session", default=None)


def _set_session(session: Optional[_Session]):
    _session_var.set(session)


def _get_session(strict: bool = True) -> Optional[_Session]:
    s = _session_var.get()
    if s is None and strict:
        raise RuntimeError(
            "No training session active. `ray_tpu.train.report()` and "
            "`get_context()` must be called inside `train_loop_per_worker`.")
    return s


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) to the trainer.

    Reference semantics (``ray.train.report``): all workers should call it at
    the same cadence; only rank-0's checkpoint is persisted by default.

    This is also the per-step boundary the elastic control loop hooks:
    the cooperative stop flag is honored here, and the chaos harness's
    ``train_step`` injection site fires here (kill/slow faults land at a
    step boundary, like a real mid-step host loss would be observed).
    """
    from ray_tpu import exceptions as _exc
    from ray_tpu._private import chaos

    s = _get_session()
    if s.stop.is_set():
        raise _exc.WorkerStoppedError(
            "worker group torn down (elastic restart in progress)")
    step = metrics.get("step")
    if not isinstance(step, int):
        step = s.report_seq
    chaos.inject("train_step", rank=s.context.get_world_rank(), step=step)
    s.report_seq += 1
    # Per-step timeline record: this step's wall time is the gap since
    # the previous report (its "dispatch"); a chaos slow_step delay
    # above lands inside it, exactly like a genuinely slow rank. The
    # record rides the report queue (one report == one step), so the
    # controller's poll merge sees rank-attributed timings for free.
    # The FIRST report's gap runs from session start — user-fn setup,
    # jit compile, checkpoint restore — not a dispatch→report gap, so
    # it is marked and excluded from rank-skew scoring.
    now_mono = time.monotonic()
    step_dur = now_mono - s.progress_ts
    s.progress_ts = now_mono
    s.last_step = step
    timing = {"step": step, "ts": time.time(), "dur": step_dur}
    if s.report_seq == 1:
        timing["first"] = True
    s.reports.put({"metrics": dict(metrics), "checkpoint": checkpoint,
                   "step_timing": timing})


def get_context() -> TrainContext:
    s = _get_session()
    return s.context


def get_checkpoint() -> Optional[Checkpoint]:
    """Checkpoint to restore from (set when recovering from failure)."""
    s = _get_session()
    return s.restore_checkpoint


def get_dataset_shard(name: str = "train"):
    """This worker's disjoint :class:`~ray_tpu.data.DataIterator` shard of
    the dataset passed as ``JaxTrainer(datasets={name: ds})`` (reference:
    ``ray.train.get_dataset_shard`` over ``Dataset.streaming_split``).

    Each worker sees only its own rows. ``iter_batches()`` yields host
    batches; ``iter_device_batches(trainer_or_sharding)`` stages them
    onto the mesh with background prefetch ON BY DEFAULT (depth 2) —
    the intended train-loop spelling::

        it = rt_train.get_dataset_shard()
        for batch in it.iter_device_batches(trainer, batch_size=8):
            loop.step(batch)
    """
    s = _get_session()
    shard = s.dataset_shards.get(name)
    if shard is None:
        have = sorted(s.dataset_shards) or "(none)"
        raise KeyError(
            f"no dataset shard named {name!r} in this session — pass "
            f"datasets={{{name!r}: ds}} to JaxTrainer (have: {have})")
    return shard


def get_checkpoint_plane(run: str = "train"):
    """This run's distributed checkpoint plane
    (:class:`ray_tpu.checkpoint.CheckpointPlane`), rooted inside the
    experiment directory and keyed by this worker's rank — every worker
    of one run participates in the same two-phase-commit manifest stream.
    Use it for async sharded saves, elastic restores, and preemption-time
    just-in-time checkpoints."""
    import os

    s = _get_session()
    if s.checkpoint_plane is None:
        if s.run_dir is None:
            raise RuntimeError(
                "this session has no run directory — "
                "get_checkpoint_plane() needs a JaxTrainer-managed run")
        from ray_tpu.checkpoint import CheckpointPlane

        ctx = s.context
        s.checkpoint_plane = CheckpointPlane(
            os.path.join(s.run_dir, "ckpt_plane"), run=run,
            process_index=ctx.get_world_rank(),
            process_count=ctx.get_world_size(),
            # Once the controller flags this session for teardown, saves
            # raise WorkerStoppedError: an abandoned loop that outlives
            # the bounded join writes to the SAME shard paths / 2PC keys
            # as the next attempt when the world size is unchanged.
            fence=s.stop.is_set)
    return s.checkpoint_plane
