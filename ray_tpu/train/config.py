"""Train/AIR configuration dataclasses.

Reference: ``python/ray/air/config.py`` (ScalingConfig/RunConfig/FailureConfig/
CheckpointConfig). TPU-native addition: ``ScalingConfig.topology`` describes
the per-worker chip ask (e.g. "v5e-8") and ``mesh`` the parallelism layout the
backend should build — the reference expresses neither because NCCL ranks are
topology-blind.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ray_tpu.parallel import MeshConfig


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each one owns.

    ``num_workers`` is the number of *processes* (actors); with TPU, each
    worker owns ``tpus_per_worker`` chips and all workers jointly run one
    SPMD program over the global mesh.
    """

    num_workers: int = 1
    # Elastic lower bound (reference: Train v2 elastic training): after a
    # failure the controller restarts with as many workers as the cluster
    # can currently supply, as long as it's at least this. None = rigid.
    min_workers: Optional[int] = None
    use_tpu: bool = False
    tpus_per_worker: Optional[float] = None
    cpus_per_worker: float = 1.0
    resources_per_worker: Optional[Dict[str, float]] = None
    topology: Optional[str] = None       # e.g. "v5e-8": slice type ask
    mesh: Optional[MeshConfig] = None    # parallelism layout over all chips
    placement_strategy: str = "PACK"
    # Form a real multi-process jax.distributed group across the worker
    # actors (worker 0 hosts the coordinator service; the address is also
    # published to the GCS KV). Off by default: single-host workers sharing
    # one jax client don't need it.
    jax_distributed: bool = False

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", self.cpus_per_worker)
        if self.use_tpu:
            res.setdefault("TPU", self.tpus_per_worker or 1.0)
        return res

    @property
    def total_workers(self) -> int:
        return self.num_workers


@dataclasses.dataclass
class FailureConfig:
    """Reference: ``air/config.py::FailureConfig``.

    ``max_failures`` governs USER exceptions only (the train loop
    raising). Infrastructure failures — worker death, hung collectives,
    lapsed heartbeats — have their own budget (``RAY_TPU_MAX_RESTARTS``),
    preemptions theirs (``RAY_TPU_MAX_PREEMPTIONS``), and worker-set
    resizes theirs (``RAY_TPU_MAX_RESIZES``); see
    ``ray_tpu/train/elastic.py`` for the full taxonomy.
    """

    max_failures: int = 0  # 0 = no retries, -1 = infinite
    # Per-step watchdog: if no worker reports for this long after the
    # first report, the attempt is declared hung (retryable under the
    # restart budget). None reads RAY_TPU_STEP_WATCHDOG_S; 0 disables.
    # Before the first report the deadline is 10x (compile headroom).
    watchdog_s: Optional[float] = None
    # Fatal-NaN guard: this many CONSECUTIVE reports with a non-finite
    # "loss" ends the run as FATAL (restarting would replay the same
    # divergence). None reads RAY_TPU_NAN_FATAL_REPORTS; 0 disables.
    nan_fatal_reports: Optional[int] = None


@dataclasses.dataclass
class CheckpointConfig:
    """Reference: ``air/config.py::CheckpointConfig`` (top-k retention)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    # Persist checkpoints on a background thread (orbax-style: one write
    # in flight; the trainer joins it before restarts/results).
    async_write: bool = False


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    verbose: int = 1

    def __post_init__(self):
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()


@dataclasses.dataclass
class Result:
    """Reference: ``air/result.py``."""

    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Any]
    path: Optional[str]
    error: Optional[BaseException] = None
    metrics_history: Optional[list] = None

    @property
    def best_checkpoints(self):
        return [(self.checkpoint, self.metrics)] if self.checkpoint else []
