"""Async step pipelining: dispatch-ahead training with windowed syncs.

The synced loop fetches the loss every step — one device→host round trip
per step, and the XLA pipe drains while the host formats a float. This
loop keeps up to ``sync_every`` steps dispatched and pulls their metrics
off-device in one windowed fetch, so the device runs back-to-back steps
while the host stays out of the hot path (the training-side analog of
the buffered serve engine's ``sync_every`` speculative decode).

Gauge honesty: ``xla_monitor``'s call-cadence fallback for the
achieved-FLOPs/MFU gauges is only right when every call syncs. This loop
disables that fallback by feeding MEASURED window wall time through
``InstrumentedJit.note_execution`` (window wall / steps in window), the
same windowed accounting the serve engine uses — so MFU stays honest
with K steps in flight.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple


class AsyncStepLoop:
    """Drive ``trainer.train_step`` with at most ``sync_every`` un-synced
    steps; metrics land in ``history`` (host floats) at each window sync.

    Exactly the same programs run as in a synced loop — only the fetch
    cadence changes, so losses are bit-identical to per-step syncing.
    """

    def __init__(self, trainer, state, *, sync_every: int = 4,
                 name: str = "async_loop", ledger=None):
        if sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        self.trainer = trainer
        self.state = state
        self.sync_every = sync_every
        self.name = name
        # Goodput attribution: the windowed fetch's host-blocked wall
        # time is the ledger's "sync" component — explicit ledger wins,
        # else the ambient training session's (resolved per sync).
        self._ledger = ledger
        self.history: List[Dict[str, float]] = []
        self.steps = 0
        self._pending: List[Dict[str, Any]] = []
        self._window_t0: Optional[float] = None
        self._window_wall_s = 0.0
        self._synced_steps = 0

    # ------------------------------------------------------------- steps
    def step(self, batch) -> None:
        """Dispatch one train step; syncs only at window boundaries."""
        if self._window_t0 is None:
            self._window_t0 = time.perf_counter()
        self.state, metrics = self.trainer.train_step(self.state, batch)
        self._pending.append(metrics)
        self.steps += 1
        if len(self._pending) >= self.sync_every:
            self.sync()

    def sync(self) -> None:
        """Fetch every pending metrics pytree (blocks until the dispatched
        steps complete) and feed the measured window cadence to the MFU
        gauges."""
        if not self._pending:
            return
        import jax

        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.train import goodput

        n = len(self._pending)
        t_fetch = time.perf_counter()
        fetched = jax.device_get(self._pending)
        now = time.perf_counter()
        # "sync" = host blocked in the windowed fetch. Under sync_every=1
        # this is where device compute drains (the honest reading is
        # "syncing too often"), with steps in flight it is pure overhead.
        ledger = self._ledger or goodput.current_ledger()
        if ledger is not None:
            ledger.note("sync", now - t_fetch)
        wall = now - self._window_t0
        # Windows are CONTIGUOUS: the next one starts here, not at its
        # first step(), so the stall fetching a window's first batch —
        # or any host work between windows — lands inside a window under
        # the direct ``loop.step(batch)`` spelling too. Idle time can
        # only inflate measured wall: MFU errs LOW, never high.
        self._window_t0 = now
        self._window_wall_s += wall
        self._synced_steps += n
        per_step = wall / n
        step_jit = getattr(self.trainer, "_step", None)
        if step_jit is not None and hasattr(step_jit, "note_execution"):
            # Windowed accounting: dispatch-of-first → fetch-complete,
            # split across the window's steps. Input stalls inside the
            # window inflate it — MFU errs LOW, never high.
            step_jit.note_execution(per_step)
        tags = {"trainer": self.name}
        for m in fetched:
            mdefs.TRAIN_STEP_SECONDS.observe(per_step, tags=tags)
            self.history.append({k: float(v) for k, v in m.items()})
        self._pending.clear()

    def run(self, batches: Iterable[Any],
            max_steps: Optional[int] = None) -> Tuple[Any, List[Dict]]:
        """Consume ``batches`` (host iterator or a
        :class:`~ray_tpu.train.ingest.DevicePrefetcher`) to exhaustion or
        ``max_steps``, then drain the window. Returns (state, history)."""
        it = iter(batches)
        while True:
            # Stamp the very first window before pulling the first batch
            # so its fetch stall is measured; sync() keeps later windows
            # contiguous from there.
            if self._window_t0 is None:
                self._window_t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            self.step(batch)
            if max_steps is not None and self.steps >= max_steps:
                break
        return self.finish()

    def finish(self) -> Tuple[Any, List[Dict]]:
        self.sync()
        return self.state, self.history

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, float]:
        return {
            "steps": float(self.steps),
            "synced_steps": float(self._synced_steps),
            "window_wall_s": self._window_wall_s,
            "step_s": (self._window_wall_s / self._synced_steps
                       if self._synced_steps else 0.0),
            "pending": float(len(self._pending)),
        }
