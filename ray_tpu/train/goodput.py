"""Training-path observability: the goodput ledger and straggler detection.

The serve plane (PR 7) answers "where did this request's latency go";
this module answers the training twin — "where did the last hour of
chip time go, and which rank is dragging the mesh".

**Goodput ledger.** :class:`GoodputLedger` partitions one training
attempt's wall clock into named, mutually exclusive components. The
model is a *host-state* partition: at any instant the training host is
either

* ``step`` — dispatching steps / free-running ahead of the device (the
  device is doing productive compute; with ``sync_every`` steps in
  flight the host's bookkeeping overlaps device work, so this residual
  is the honest "productive" bucket),
* ``input_stall`` — blocked on an empty device-prefetch buffer
  (:class:`~ray_tpu.train.ingest.DevicePrefetcher` notes its measured
  consumer-side stall here),
* ``sync`` — blocked in the windowed metric fetch
  (:class:`~ray_tpu.train.loop.AsyncStepLoop` notes its
  ``jax.device_get`` wall time; in a per-step-sync loop this is where
  device compute *drains*, so a large ``sync`` fraction under
  ``sync_every=1`` reads "raise sync_every", not "the device is idle"),
* ``ckpt_block`` — blocked in the checkpoint plane's device→host
  snapshot (the only synchronous leg of ``save_async``; the
  ``ray_tpu_ckpt_block_ms`` histogram existed but was unattributed), or
* ``recovery`` — the worker-side restore leg of an elastic recovery
  (``CheckpointPlane.restore`` wall time). The full
  detection→teardown→re-acquire→re-form→restore→first-step recovery is
  controller-side and lands in ``ray_tpu_train_recovery_seconds`` and
  the ``train.recovery`` trace; the ledger's slice is the part that
  spends *this attempt's* wall clock.

``step`` is computed as the residual (wall − every measured non-step
component), so the components sum to the measured attempt wall time BY
CONSTRUCTION — and the invariant is still a real tripwire: any
double-counted interval (e.g. an input stall also booked as sync)
drives ``step`` negative and fails the 1% acceptance test.

Each worker session owns one ledger (``_Session.ledger``); instrumented
sites attribute through :func:`note_ambient`, which resolves the active
session's ledger (no-op outside a training session, e.g. in benches
that pass an explicit ledger instead). The controller reads snapshots
off the ``poll()`` path and feeds ``ray_tpu_train_goodput_seconds_total
{component}`` / ``ray_tpu_train_goodput_fraction{component}``.

**Straggler detection.** Every ``session.report`` records the step's
per-rank wall time (dispatch→report). The controller merges them into
fixed-size step windows; when every rank has moved past window *w* the
window is scored: a rank whose mean step time exceeds
``RAY_TPU_STRAGGLER_FACTOR`` (default 2.0) times the window median
(``median_low`` — robust down to world size 2) for
``RAY_TPU_STRAGGLER_WINDOWS`` (default 3) CONSECUTIVE windows is
flagged: published to the GCS ``__train__`` KV, surfaced as
``ray_tpu_train_straggler{rank}``, and logged by the controller. A rank
that drops back under the factor is cleared. Window size is
``RAY_TPU_STRAGGLER_WINDOW_STEPS`` (default 4) steps.
"""

from __future__ import annotations

import os
import statistics
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = ["COMPONENTS", "GoodputLedger", "StragglerDetector",
           "current_ledger", "note_ambient"]

# Badput components a site can note; "step" is always the residual.
COMPONENTS = ("input_stall", "sync", "ckpt_block", "recovery")


class GoodputLedger:
    """Wall-clock partition of one training attempt (see module doc)."""

    def __init__(self, name: str = "train"):
        self.name = name
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._closed_wall: Optional[float] = None
        self._acc: Dict[str, float] = {c: 0.0 for c in COMPONENTS}

    def note(self, component: str, seconds: float) -> None:
        """Attribute ``seconds`` of wall time to a non-step component."""
        if component not in self._acc:
            raise ValueError(
                f"unknown goodput component {component!r} "
                f"(known: {COMPONENTS}; 'step' is the residual)")
        if seconds > 0:
            with self._lock:
                self._acc[component] += seconds

    @contextmanager
    def component(self, name: str):
        """Measure a block and attribute it: ``with ledger.component(
        "input_stall"): batch = next(it)``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.note(name, time.perf_counter() - t0)

    def close(self) -> None:
        """Freeze the wall clock (the attempt ended)."""
        with self._lock:
            if self._closed_wall is None:
                self._closed_wall = time.perf_counter() - self._t0

    def wall_s(self) -> float:
        with self._lock:
            return (self._closed_wall
                    if self._closed_wall is not None
                    else time.perf_counter() - self._t0)

    def snapshot(self) -> Dict[str, Any]:
        """``{"wall_s", "components": {step, input_stall, sync,
        ckpt_block, recovery}}`` — components sum to ``wall_s`` exactly
        (``step`` is the residual and may go NEGATIVE if a site
        double-books an interval; tests treat that as corruption)."""
        with self._lock:
            wall = (self._closed_wall
                    if self._closed_wall is not None
                    else time.perf_counter() - self._t0)
            comps = dict(self._acc)
        comps["step"] = wall - sum(comps.values())
        return {"wall_s": wall, "components": comps}

    def fractions(self) -> Dict[str, float]:
        snap = self.snapshot()
        wall = max(snap["wall_s"], 1e-9)
        return {c: v / wall for c, v in snap["components"].items()}


# ------------------------------------------------------- ambient ledger
def current_ledger() -> Optional[GoodputLedger]:
    """The active training session's ledger, if this thread is inside
    one (``TrainWorker.run`` sets the session contextvar)."""
    try:
        from ray_tpu.train import session as session_mod
    except Exception:  # noqa: BLE001 — partial import during teardown
        return None
    s = session_mod._get_session(strict=False)
    return None if s is None else getattr(s, "ledger", None)


def note_ambient(component: str, seconds: float) -> None:
    """Attribute time to the ambient session ledger; no-op outside a
    training session. Instrumented sites (ingest prefetcher, checkpoint
    plane) call this so raw/bench usage costs one contextvar read."""
    led = current_ledger()
    if led is not None:
        led.note(component, seconds)


# --------------------------------------------------- straggler detection
def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


class StragglerDetector:
    """Per-window rank-skew scoring over per-rank step times.

    ``observe(rank, step, dur, ts)`` accumulates one rank's step wall
    time; it returns the list of window summaries that COMPLETED with
    this observation (a window completes when every rank has moved past
    it — scoring earlier would compare a finished rank against a
    straggler's partial window). Each summary carries the per-rank
    means, the window median (``median_low``), the max skew, and the
    flag transitions the controller must publish."""

    def __init__(self, world_size: int, *,
                 factor: Optional[float] = None,
                 consecutive: Optional[int] = None,
                 window_steps: Optional[int] = None):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        self.world = world_size
        self.factor = (factor if factor is not None
                       else _env_float("RAY_TPU_STRAGGLER_FACTOR", 2.0))
        self.consecutive = (consecutive if consecutive is not None
                            else _env_int("RAY_TPU_STRAGGLER_WINDOWS", 3))
        self.window_steps = (
            window_steps if window_steps is not None
            else _env_int("RAY_TPU_STRAGGLER_WINDOW_STEPS", 4))
        if self.window_steps < 1 or self.consecutive < 1:
            raise ValueError("window_steps and consecutive must be >= 1")
        # window -> rank -> [durs]; wall-ts bounds per window.
        self._durs: Dict[int, Dict[int, List[float]]] = {}
        self._bounds: Dict[int, List[float]] = {}
        self._max_window: Dict[int, int] = {}
        self._streak: Dict[int, int] = {r: 0 for r in range(world_size)}
        self._next_eval: Optional[int] = None
        self.flagged: Dict[int, Dict[str, Any]] = {}
        self.windows_scored = 0

    def observe(self, rank: int, step: int, dur: float,
                ts: Optional[float] = None) -> List[Dict[str, Any]]:
        if rank < 0 or rank >= self.world:
            return []
        w = int(step) // self.window_steps
        self._durs.setdefault(w, {}).setdefault(rank, []).append(
            float(dur))
        if ts is not None:
            start = float(ts) - float(dur)
            b = self._bounds.setdefault(w, [start, float(ts)])
            b[0] = min(b[0], start)
            b[1] = max(b[1], float(ts))
        prev = self._max_window.get(rank)
        self._max_window[rank] = w if prev is None else max(prev, w)
        if self._next_eval is None:
            self._next_eval = w
        out: List[Dict[str, Any]] = []
        # A window is scoreable once EVERY rank has reported from a
        # LATER window (all its steps for the window are in).
        while (len(self._max_window) == self.world
               and min(self._max_window.values()) > self._next_eval):
            summary = self._evaluate(self._next_eval)
            if summary is not None:
                out.append(summary)
            self._next_eval += 1
        return out

    def _evaluate(self, w: int) -> Optional[Dict[str, Any]]:
        per_rank = self._durs.pop(w, {})
        bounds = self._bounds.pop(w, None)
        if len(per_rank) < self.world:
            # A rank skipped the window entirely (restore fast-forwarded
            # its step counter) — nothing comparable to score.
            return None
        means = {r: sum(d) / len(d) for r, d in per_rank.items()}
        med = statistics.median_low(sorted(means.values()))
        newly, cleared = [], []
        for r, m in means.items():
            slow = med > 0 and m > self.factor * med
            if slow:
                self._streak[r] = self._streak.get(r, 0) + 1
                if (self._streak[r] >= self.consecutive
                        and r not in self.flagged):
                    self.flagged[r] = {
                        "rank": r, "window": w, "mean_s": m,
                        "median_s": med, "skew": m / med,
                        "streak": self._streak[r], "ts": time.time()}
                    newly.append(r)
            else:
                self._streak[r] = 0
                if r in self.flagged:
                    del self.flagged[r]
                    cleared.append(r)
        self.windows_scored += 1
        return {
            "window": w,
            "means": means,
            "median_s": med,
            "max_skew": (max(means.values()) / med) if med > 0 else 0.0,
            "start_ts": bounds[0] if bounds else None,
            "end_ts": bounds[1] if bounds else None,
            "newly_flagged": newly,
            "cleared": cleared,
            "flagged": sorted(self.flagged),
        }
