"""Checkpoint storage: pyarrow-fs persistence + async writes.

Reference: ``train/_internal/storage.py:358`` (``StorageContext`` — local ↔
cloud filesystem paths via pyarrow.fs) and the orbax-style async
checkpointing the reference reaches through Train's checkpoint upload
path: the device→host snapshot is taken synchronously (so the saved state
is consistent even if training mutates it immediately after), while
serialization and the filesystem write happen on a background thread that
the trainer only joins at the next save or at shutdown.
"""

from __future__ import annotations

import os
import pickle
import posixpath
import shutil
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Optional

_URI_SEP = "://"


class StorageContext:
    """Resolves an experiment's storage root onto a pyarrow FileSystem.

    ``storage_path`` may be a plain local path or a pyarrow-fs URI
    (``file:///...``, ``s3://bucket/...``); uploads/downloads then work
    against whichever filesystem backs it.
    """

    def __init__(self, storage_path: str, experiment_name: str):
        from pyarrow import fs as pafs

        if _URI_SEP in storage_path:
            self.fs, base = pafs.FileSystem.from_uri(storage_path)
        else:
            self.fs = pafs.LocalFileSystem()
            base = os.path.abspath(storage_path)
        self.storage_path = storage_path
        self.experiment_dir = posixpath.join(base, experiment_name)
        self.fs.create_dir(self.experiment_dir, recursive=True)

    def join(self, *parts: str) -> str:
        return posixpath.join(self.experiment_dir, *parts)

    def upload_dir(self, local_dir: str, remote_rel: str) -> str:
        """Recursively copy ``local_dir`` under the experiment dir; returns
        the storage path of the uploaded directory."""
        dest_root = self.join(remote_rel)
        self.fs.create_dir(dest_root, recursive=True)
        for root, _, files in os.walk(local_dir):
            rel = os.path.relpath(root, local_dir)
            droot = dest_root if rel == "." else posixpath.join(
                dest_root, rel.replace(os.sep, "/"))
            self.fs.create_dir(droot, recursive=True)
            for fname in files:
                with open(os.path.join(root, fname), "rb") as src, \
                        self.fs.open_output_stream(
                            posixpath.join(droot, fname)) as dst:
                    shutil.copyfileobj(src, dst, 1 << 20)
        return dest_root

    def download_dir(self, remote_path: str, local_dir: str) -> str:
        """Copy a stored directory back to ``local_dir``."""
        from pyarrow import fs as pafs

        os.makedirs(local_dir, exist_ok=True)
        selector = pafs.FileSelector(remote_path, recursive=True)
        for entry in self.fs.get_file_info(selector):
            rel = posixpath.relpath(entry.path, remote_path)
            target = os.path.join(local_dir, rel)
            if entry.type == pafs.FileType.Directory:
                os.makedirs(target, exist_ok=True)
                continue
            os.makedirs(os.path.dirname(target), exist_ok=True)
            with self.fs.open_input_stream(entry.path) as src, \
                    open(target, "wb") as dst:
                shutil.copyfileobj(src, dst, 1 << 20)
        return local_dir

    def delete_dir(self, remote_path: str) -> None:
        from pyarrow import fs as pafs

        if self.fs.get_file_info(remote_path).type != pafs.FileType.NotFound:
            self.fs.delete_dir(remote_path)

    def exists(self, remote_path: str) -> bool:
        from pyarrow import fs as pafs

        info = self.fs.get_file_info(remote_path)
        return info.type != pafs.FileType.NotFound


class AsyncCheckpointer:
    """Orbax-style async checkpoint writer.

    ``save()`` snapshots device arrays to host *synchronously* (the part
    that must be consistent with the training step), then hands
    serialization + the write to a single background thread. A new save
    first waits for the previous one — at most one write is ever in
    flight, matching orbax AsyncCheckpointer semantics — so checkpoints
    can never interleave on disk.
    """

    def __init__(self, storage: Optional[StorageContext] = None):
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="async-ckpt")
        self._pending: Optional[Future] = None
        self._lock = threading.Lock()
        self.storage = storage

    def save(self, tree: Any, directory: str, name: str = "state",
             upload_rel: Optional[str] = None) -> Future:
        """Snapshot now, write later. Returns the write's Future (resolves
        to the checkpoint directory, or the storage path if uploaded)."""
        import jax
        import numpy as np

        self.wait()  # one write in flight, in order
        leaves, treedef = jax.tree.flatten(tree)
        # Snapshot point: np.array COPIES (np.asarray would alias numpy
        # leaves, letting in-place mutation after save() corrupt the
        # checkpoint the background thread is still serializing).
        host_leaves = [np.array(x) for x in leaves]

        def write() -> str:
            os.makedirs(directory, exist_ok=True)
            tmp = os.path.join(directory, f".{name}.npz.tmp")
            with open(tmp, "wb") as f:
                np.savez(f, **{str(i): a for i, a in
                               enumerate(host_leaves)})
            os.replace(tmp, os.path.join(directory, f"{name}.npz"))
            with open(os.path.join(directory,
                                   f"{name}.treedef.pkl"), "wb") as f:
                pickle.dump(treedef, f)
            if self.storage is not None and upload_rel is not None:
                return self.storage.upload_dir(directory, upload_rel)
            return directory

        fut = self._executor.submit(write)
        with self._lock:
            self._pending = fut
        return fut

    def wait(self) -> None:
        """Block until the in-flight write (if any) completes; re-raises
        its error so a failed persist is never silent."""
        with self._lock:
            fut = self._pending
            self._pending = None
        if fut is not None:
            fut.result()

    def close(self) -> None:
        self.wait()
        self._executor.shutdown(wait=True)
