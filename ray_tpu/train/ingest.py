"""Training ingest: host→device prefetch with a bounded background buffer.

The train-loop anti-pattern this kills: ``shard_batch`` inside the step
loop runs host batch assembly + a synchronous ``jax.device_put`` while
the chips sit idle, then the per-step loss fetch syncs the pipe — at
BENCH_r05 that host leg was ~11% of wall time. :class:`DevicePrefetcher`
wraps ANY host batch iterator (``Dataset.iter_batches``, a
``streaming_split`` shard, a synthetic generator) and stages batches
onto the mesh on a background thread through a bounded double/triple
buffer, so the H2D transfer of batch N+1 overlaps the compute of step N
(reference: ``python/ray/train`` ingest over the ``python/ray/data``
streaming executor; jax device-prefetch idiom à la flax
``jax_utils.prefetch_to_device``).

Accounting is first-class: the consumer-side blocked time is the
**input stall** (``ray_tpu_train_input_stall_seconds`` — its sum over
the run divided by wall time is the input-stall fraction the bench
reports), buffer occupancy is a gauge, and staged bytes feed the
data-plane bytes/s counter.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import numpy as np

_SENTINEL = object()


def _resolve_sharding(sharding):
    """Accept a NamedSharding (or anything device_put takes) OR an object
    that carries one (``ShardedTrainer.batch_sharding``)."""
    if sharding is not None and hasattr(sharding, "batch_sharding"):
        return sharding.batch_sharding
    return sharding


def _batch_nbytes(batch) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(batch):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


class DevicePrefetcher:
    """Bounded background host→device staging over a batch iterator.

    One producer thread pulls host batches from ``source``, applies
    ``decode_fn`` (host-side decode/augment), and issues a sharded
    ``jax.device_put`` onto ``sharding``; results queue into a
    ``depth``-bounded buffer (depth=2 is classic double buffering,
    depth=3 absorbs jittery producers). The consumer iterates device
    batches in source order. Exceptions raised by the source or the
    decode propagate to the consumer at the batch position where they
    occurred; ``close()`` (or exhaustion) reclaims the thread — no
    leaked daemon keeps device buffers alive.
    """

    def __init__(self, source: Iterable[Any], sharding=None, *,
                 depth: int = 2,
                 decode_fn: Optional[Callable[[Any], Any]] = None,
                 name: str = "train", ledger=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.name = name
        self.depth = depth
        # Goodput attribution: consumer-side stalls land in a ledger —
        # the one passed explicitly (benches/tests), else the ambient
        # training session's (resolved per get; no-op outside one).
        self._ledger = ledger
        self._source = iter(source)
        self._sharding = _resolve_sharding(sharding)
        self._decode = decode_fn
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._closed = False
        self._exhausted = False
        # -- accounting ------------------------------------------------
        self._lock = threading.Lock()
        self._stall_s = 0.0       # consumer blocked on an empty buffer
        self._put_wall_s = 0.0    # producer decode + device_put issue
        self._batches_out = 0
        self._bytes_in = 0
        self._occ_sum = 0.0       # occupancy sampled at each get
        self._started = time.perf_counter()
        self._thread = threading.Thread(
            target=self._produce, daemon=True,
            name=f"rtpu-prefetch-{name}")
        self._thread.start()

    # ----------------------------------------------------------- producer
    def _produce(self) -> None:
        import jax

        from ray_tpu._private import metrics_defs as mdefs

        tags = {"iterator": self.name}
        try:
            for batch in self._source:
                if self._stop.is_set():
                    return
                t0 = time.perf_counter()
                if self._decode is not None:
                    batch = self._decode(batch)
                nbytes = _batch_nbytes(batch)
                if self._sharding is not None:
                    batch = jax.device_put(batch, self._sharding)
                else:
                    batch = jax.device_put(batch)
                with self._lock:
                    self._put_wall_s += time.perf_counter() - t0
                mdefs.TRAIN_INGEST_BYTES.inc(nbytes, tags=tags)
                # Bytes ride the queue item and land in stats() at GET
                # time: reset_stats() defines a consumption window, so
                # batches already staged into the warm buffer must count
                # when consumed, not when produced (the monotonic counter
                # above keeps producer-side semantics).
                self._blocking_put(("ok", batch, nbytes))
                mdefs.TRAIN_PREFETCH_OCCUPANCY.set(
                    self._q.qsize() / self.depth, tags=tags)
        except BaseException as e:  # noqa: BLE001 — propagate to consumer
            self._blocking_put(("err", e, 0))
            return
        self._blocking_put(("end", _SENTINEL, 0))

    def _blocking_put(self, item) -> None:
        """Bounded put that stays responsive to close()."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # ----------------------------------------------------------- consumer
    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        from ray_tpu._private import metrics_defs as mdefs

        if self._exhausted or self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        kind, payload, nbytes = self._q.get()
        stall = time.perf_counter() - t0
        tags = {"iterator": self.name}
        mdefs.TRAIN_INPUT_STALL.observe(stall, tags=tags)
        from ray_tpu.train import goodput

        if self._ledger is not None:
            self._ledger.note("input_stall", stall)
        else:
            goodput.note_ambient("input_stall", stall)
        mdefs.TRAIN_PREFETCH_OCCUPANCY.set(
            self._q.qsize() / self.depth, tags=tags)
        with self._lock:
            self._stall_s += stall
            if kind == "ok":
                self._occ_sum += self._q.qsize() / self.depth
                self._batches_out += 1
                self._bytes_in += nbytes
        if kind == "end":
            self._exhausted = True
            self._join()
            raise StopIteration
        if kind == "err":
            self._exhausted = True
            self._join()
            raise payload
        return payload

    # ------------------------------------------------------------ control
    def close(self) -> None:
        """Stop the producer and drop buffered device batches. Safe to
        call mid-stream, twice, or after exhaustion."""
        self._closed = True
        self._stop.set()
        # Drain so a producer blocked on a full buffer can observe stop.
        self._drain()
        self._join()
        # Re-drain after the join: a put already past the stop check may
        # have landed an item between the first drain and thread exit —
        # it would otherwise stay buffered (pinning device memory) since
        # __next__ short-circuits once closed.
        self._drain()
        # Wake a consumer blocked in __next__'s q.get() (close() from
        # another thread): the producer is gone and will never enqueue
        # the end sentinel, so deliver it here. Queue is empty post-
        # drain, so this never blocks; a consumer that checks _closed
        # first simply leaves the sentinel behind — it pins nothing.
        try:
            self._q.put_nowait(("end", _SENTINEL, 0))
        except queue.Full:  # pragma: no cover - post-drain queue is empty
            pass

    def _drain(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def _join(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # Producer wedged inside next(source) (e.g. a slow
                # object-store fetch): it can still land one batch
                # post-drain. Make the leak observable, don't hang.
                import logging

                logging.getLogger(__name__).warning(
                    "prefetcher %r: producer thread still alive after "
                    "5s join — source iterator is blocked; a late "
                    "batch may stay buffered until GC", self.name)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # belt-and-braces: tests assert explicit close
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------- stats
    def reset_stats(self) -> None:
        """Zero the accounting window (call after warmup so compile-time
        stalls don't pollute the measured stall fraction)."""
        with self._lock:
            self._stall_s = 0.0
            self._put_wall_s = 0.0
            self._batches_out = 0
            self._bytes_in = 0
            self._occ_sum = 0.0
            self._started = time.perf_counter()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            elapsed = max(time.perf_counter() - self._started, 1e-9)
            n = self._batches_out
            return {
                "batches": float(n),
                "input_stall_s": self._stall_s,
                "input_stall_frac": min(self._stall_s / elapsed, 1.0),
                "producer_put_s": self._put_wall_s,
                "bytes_staged": float(self._bytes_in),
                "bytes_per_s": self._bytes_in / elapsed,
                "avg_occupancy": (self._occ_sum / n) if n else 0.0,
                "buffer_depth": float(self.depth),
                "buffered_now": float(self._q.qsize()),
            }


def prefetch_to_device(source: Iterable[Any], sharding=None, *,
                       depth: int = 2,
                       decode_fn: Optional[Callable[[Any], Any]] = None,
                       name: str = "train") -> DevicePrefetcher:
    """Functional spelling of :class:`DevicePrefetcher` for generator
    pipelines: ``for batch in prefetch_to_device(ds.iter_batches(...),
    trainer): ...``."""
    return DevicePrefetcher(source, sharding, depth=depth,
                            decode_fn=decode_fn, name=name)


def synthetic_host_batches(batch_size: int, seq_len: int, vocab_size: int,
                           steps: Optional[int] = None, seed: int = 0
                           ) -> Iterator[Dict[str, np.ndarray]]:
    """Host-side (numpy) synthetic LM batches — the prefetcher's input in
    benches and tests, shaped like ``Dataset.iter_batches`` output."""
    rng = np.random.default_rng(seed)
    produced = 0
    while steps is None or produced < steps:
        tokens = rng.integers(0, vocab_size, (batch_size, seq_len),
                              dtype=np.int32)
        yield {"tokens": tokens, "mask": np.ones_like(tokens)}
        produced += 1
