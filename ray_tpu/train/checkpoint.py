"""Checkpoint: directory-backed training state (reference: ``train/_checkpoint.py:56``).

A ``Checkpoint`` is a handle to a directory (``from_directory``/
``to_directory``/``as_directory`` mirror the reference API at
``train/_checkpoint.py:179,190,234``). Helpers save/restore jax pytrees with
numpy container files; sharded arrays are fetched to host before writing and
re-sharded by the caller on restore (orbax-style async/multi-host checkpointing
layers on top in the cluster runtime).
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    # -- accessors ---------------------------------------------------------
    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            return self.path
        path = os.path.abspath(path)
        if path != self.path:
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, "metadata.json"), "w") as f:
            json.dump(metadata, f)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, "metadata.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


def save_pytree(tree: Any, directory: str, name: str = "state") -> None:
    """Save a jax pytree: arrays to .npz, structure via pickle of treedef paths."""
    import jax
    import numpy as np

    os.makedirs(directory, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    host_leaves = [np.asarray(x) for x in leaves]
    np.savez(os.path.join(directory, f"{name}.npz"),
             **{str(i): a for i, a in enumerate(host_leaves)})
    with open(os.path.join(directory, f"{name}.treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def load_pytree(directory: str, name: str = "state") -> Any:
    import jax
    import numpy as np

    with open(os.path.join(directory, f"{name}.treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    data = np.load(os.path.join(directory, f"{name}.npz"))
    leaves = [data[str(i)] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)


class CheckpointManager:
    """Top-k checkpoint retention (reference: ``_internal/checkpoint_manager.py``).

    ``async_write=True`` moves the copy-to-root (and the optional
    ``storage`` upload — a :class:`~ray_tpu.train.storage.StorageContext`)
    onto a background thread, orbax-style: at most one persist in flight,
    and :meth:`flush` joins it before anyone reads ``latest``/``best``.
    """

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max", async_write: bool = False,
                 storage=None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self.storage = storage
        self._ckpts: list = []  # (score, path, metrics)
        self._executor = None
        self._pending = None
        if async_write:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-persist")

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
        metrics = metrics or {}
        dest = os.path.join(self.root, f"checkpoint_{uuid.uuid4().hex[:8]}")
        # The local copy is ALWAYS synchronous: callers may reuse/mutate
        # the source directory right after register(), so a background
        # copy would capture mixed state. Async mode offloads only the
        # storage upload — the slow leg — which reads the stable `dest`.
        checkpoint.to_directory(dest)

        def persist():
            if self.storage is not None:
                self.storage.upload_dir(dest, os.path.basename(dest))
            return dest

        if self._executor is not None:
            self.flush()  # one persist in flight, in submission order
            if self.storage is not None:
                self._pending = (self._executor.submit(persist), dest)
        else:
            persist()
        persisted = Checkpoint(dest)
        score = metrics.get(self.score_attribute) if self.score_attribute else None
        self._ckpts.append((score, persisted, metrics))
        self._evict()
        return persisted

    def flush(self) -> None:
        """Join the in-flight async persist. A failed persist is dropped
        from the retention list (its directory never completed) before the
        error re-raises, so ``latest``/``best`` can't hand out a
        half-written checkpoint."""
        pending, self._pending = self._pending, None
        if pending is None:
            return
        fut, dest = pending
        try:
            fut.result()
        except Exception:
            self._ckpts = [c for c in self._ckpts if c[1].path != dest]
            raise

    def close(self) -> None:
        """Join outstanding persists and release the worker thread."""
        try:
            self.flush()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None

    def _evict(self):
        if self.num_to_keep is None or len(self._ckpts) <= self.num_to_keep:
            return
        if self.score_attribute:
            reverse = self.score_order == "max"
            ordered = sorted(
                self._ckpts,
                key=lambda t: (t[0] is not None, t[0]),
                reverse=reverse,
            )
        else:
            ordered = list(self._ckpts)  # FIFO: oldest evicted first
            ordered.reverse()
        keep = ordered[: self.num_to_keep] if self.score_attribute else \
            self._ckpts[-self.num_to_keep:]
        drop = [c for c in self._ckpts if not any(c[1] is k[1] for k in keep)]
        # Flush only when a dropped directory is the one still being
        # persisted (possible with score-based eviction); the common FIFO
        # case keeps async writes actually asynchronous.
        if self._pending is not None and any(
                c[1].path == self._pending[1] for c in drop):
            self.flush()
        for _, ckpt, _ in drop:
            shutil.rmtree(ckpt.path, ignore_errors=True)
            if self.storage is not None:
                # num_to_keep governs the mirror too, or remote usage
                # grows without bound.
                try:
                    self.storage.delete_dir(self.storage.join(
                        os.path.basename(ckpt.path)))
                except Exception:  # noqa: BLE001 — best-effort prune
                    pass
        self._ckpts = [c for c in self._ckpts if any(c[1] is k[1] for k in keep)]

    @property
    def latest(self) -> Optional[Checkpoint]:
        return self._ckpts[-1][1] if self._ckpts else None

    @property
    def best(self) -> Optional[Checkpoint]:
        if not self._ckpts:
            return None
        if not self.score_attribute:
            return self.latest
        scored = [c for c in self._ckpts if c[0] is not None]
        if not scored:
            return self.latest
        pick = max if self.score_order == "max" else min
        return pick(scored, key=lambda t: t[0])[1]
