"""Checkpoint: directory-backed training state (reference: ``train/_checkpoint.py:56``).

A ``Checkpoint`` is a handle to a directory (``from_directory``/
``to_directory``/``as_directory`` mirror the reference API at
``train/_checkpoint.py:179,190,234``). Helpers save/restore jax pytrees with
numpy container files; sharded arrays are fetched to host before writing and
re-sharded by the caller on restore (orbax-style async/multi-host checkpointing
layers on top in the cluster runtime).
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    # -- accessors ---------------------------------------------------------
    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            return self.path
        path = os.path.abspath(path)
        if path != self.path:
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, "metadata.json"), "w") as f:
            json.dump(metadata, f)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, "metadata.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


def save_pytree(tree: Any, directory: str, name: str = "state") -> None:
    """Save a jax pytree: arrays to .npz, structure via pickle of treedef paths."""
    import jax
    import numpy as np

    os.makedirs(directory, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    host_leaves = [np.asarray(x) for x in leaves]
    np.savez(os.path.join(directory, f"{name}.npz"),
             **{str(i): a for i, a in enumerate(host_leaves)})
    with open(os.path.join(directory, f"{name}.treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def load_pytree(directory: str, name: str = "state") -> Any:
    import jax
    import numpy as np

    with open(os.path.join(directory, f"{name}.treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    data = np.load(os.path.join(directory, f"{name}.npz"))
    leaves = [data[str(i)] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)


class CheckpointManager:
    """Top-k checkpoint retention (reference: ``_internal/checkpoint_manager.py``)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._ckpts: list = []  # (score, path, metrics)

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
        metrics = metrics or {}
        dest = os.path.join(self.root, f"checkpoint_{uuid.uuid4().hex[:8]}")
        persisted = Checkpoint(checkpoint.to_directory(dest))
        score = metrics.get(self.score_attribute) if self.score_attribute else None
        self._ckpts.append((score, persisted, metrics))
        self._evict()
        return persisted

    def _evict(self):
        if self.num_to_keep is None or len(self._ckpts) <= self.num_to_keep:
            return
        if self.score_attribute:
            reverse = self.score_order == "max"
            ordered = sorted(
                self._ckpts,
                key=lambda t: (t[0] is not None, t[0]),
                reverse=reverse,
            )
        else:
            ordered = list(self._ckpts)  # FIFO: oldest evicted first
            ordered.reverse()
        keep = ordered[: self.num_to_keep] if self.score_attribute else \
            self._ckpts[-self.num_to_keep:]
        drop = [c for c in self._ckpts if not any(c[1] is k[1] for k in keep)]
        for _, ckpt, _ in drop:
            shutil.rmtree(ckpt.path, ignore_errors=True)
        self._ckpts = [c for c in self._ckpts if any(c[1] is k[1] for k in keep)]

    @property
    def latest(self) -> Optional[Checkpoint]:
        return self._ckpts[-1][1] if self._ckpts else None

    @property
    def best(self) -> Optional[Checkpoint]:
        if not self._ckpts:
            return None
        if not self.score_attribute:
            return self.latest
        scored = [c for c in self._ckpts if c[0] is not None]
        if not scored:
            return self.latest
        pick = max if self.score_order == "max" else min
        return pick(scored, key=lambda t: t[0])[1]
