"""Elastic training control: failure taxonomy, resize signals, budgets.

Reference blueprint: Ray Train v2 elastic worker groups + the GCS
fault-tolerance machinery (``train/v2/_internal/execution/controller``):
the controller classifies every attempt-ending exception into a *cause*
and charges the matching budget — infrastructure loss is routine and
retried generously, user bugs are governed by ``FailureConfig`` exactly
as before, and genuinely fatal conditions (repeated NaN, an environment
that cannot bootstrap) never burn a retry.

========================  ==============================================
cause                      budget / behavior
========================  ==============================================
``worker_lost``            actor/process/node death — ``RAY_TPU_MAX_RESTARTS``
                           with exponential backoff
``hang``                   step watchdog or lapsed heartbeats — same budget
``preemption``             cooperative ``PreemptedError`` after a JIT save —
                           ``RAY_TPU_MAX_PREEMPTIONS``, no backoff
``resize``                 worker-set grow/shrink request — ``RAY_TPU_MAX_RESIZES``,
                           no backoff
``user``                   worker-surfaced task error (the train loop
                           raised) — ``FailureConfig.max_failures``
                           (unchanged semantics)
``fatal``                  repeated NaN, jax.distributed bootstrap failure,
                           or a controller-side defect — no retry, no
                           budget consumed
========================  ==============================================

Resize signals ride the existing preemption pubsub channel
(``ray_tpu/checkpoint/preempt.py``): :func:`request_resize` publishes a
notice carrying ``world_target``, and the GCS health loop publishes
``kind="capacity"`` grow hints when alive-node capacity increases
(``_private/gcs/server.py``). :class:`ResizeGuard` latches both for the
controller.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions

logger = logging.getLogger(__name__)

# Failure causes (the `cause` tag on ray_tpu_train_restarts_total).
WORKER_LOST = "worker_lost"
HANG = "hang"
PREEMPTION = "preemption"
RESIZE = "resize"
USER = "user"
FATAL = "fatal"


class ResizeRequested(exceptions.RayTpuError):
    """Internal control-flow signal: the worker set should be re-formed at
    ``world_target`` workers (raised by the controller's drive loop when a
    resize hint lands or capacity for a grow-back appears)."""

    def __init__(self, world_target: int, reason: str = "resize requested"):
        self.world_target = int(world_target)
        self.reason = reason
        super().__init__(f"{reason}: world_target={world_target}")


def classify_failure(exc: BaseException) -> str:
    """Map an attempt-ending exception to its failure cause."""
    if isinstance(exc, ResizeRequested):
        return RESIZE
    if isinstance(exc, exceptions.PreemptedError):
        return PREEMPTION
    if isinstance(exc, (exceptions.ActorDiedError,
                        exceptions.WorkerCrashedError,
                        exceptions.NodeDiedError,
                        exceptions.ActorUnavailableError)):
        return WORKER_LOST
    if isinstance(exc, exceptions.WorkerHangError):
        return HANG
    if isinstance(exc, (exceptions.NaNLossError,
                        exceptions.JaxDistributedBootstrapError)):
        return FATAL
    if isinstance(exc, exceptions.RayTaskError):
        # Surfaced through the task-error path: the user's train loop
        # failing; FailureConfig.max_failures governs it.
        return USER
    # Anything else reaching the controller is a controller/framework
    # defect or an I/O failure in the drive loop — retrying would replay
    # it, and billing it to the user's budget would mislabel it.
    return FATAL


def request_resize(num_workers: int, reason: str = "operator-resize",
                   gcs_address: Optional[str] = None) -> Dict[str, Any]:
    """Ask running elastic trainers to re-form at ``num_workers`` workers.

    Publishes on the preemption pubsub channel (cluster-wide when a GCS is
    reachable, synchronously to in-process listeners otherwise). Trainers
    latch it through their :class:`ResizeGuard`, tear the group down at a
    step boundary, and restart from the newest committed manifest at the
    new world size."""
    from ray_tpu._private import events as _events
    from ray_tpu.checkpoint.preempt import publish_preempt

    resize_ev = _events.emit("train.resize",
                             world_target=int(num_workers), reason=reason)
    return publish_preempt(reason=reason, gcs_address=gcs_address,
                           world_target=int(num_workers), cause=resize_ev)


class RecoveryTrace:
    """Controller-side bookkeeping for ONE elastic recovery, emitted as
    a connected trace when the restarted attempt's first report lands.

    The controller walks the restart path phase by phase — teardown
    (group stop + kill + zombie join), backoff sleep, re-acquire
    (worker actors + backend ``on_start`` = jax.distributed mesh
    re-formation) — and :meth:`close` turns them into retrospective
    spans: one ``train.recovery`` parent whose children tile its
    duration exactly, the tail (``restore_first_step``: restore from
    the newest intact manifest through the first post-restore report)
    being the residual. The parent's duration is the SAME value
    observed into ``ray_tpu_train_recovery_seconds``, so the trace and
    the metric can never drift apart."""

    def __init__(self, trace_id: str, parent_span_id: str, run: str,
                 cause: str, attempt: int, cause_event: str = ""):
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.run = run
        self.cause = cause
        self.attempt = attempt
        # Flight-recorder id of the event that killed the attempt (a
        # preemption notice id off PreemptedError.notice, or a chaos
        # injection's SimulatedProcessDeath.event_id), linking this
        # recovery into the cluster-wide causal chain.
        self.cause_event = cause_event
        self.t0_wall = time.time()
        self.phases: List[Tuple[str, float]] = []  # ordered (name, dur)

    def phase(self, name: str, dur_s: float) -> None:
        self.phases.append((name, max(float(dur_s), 0.0)))

    @contextmanager
    def timed_phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phase(name, time.perf_counter() - t0)

    def close(self, recovery_s: float,
              outcome: str = "recovered") -> str:
        """Emit the recovery span tree; returns the parent span id
        ('' with tracing off). ``outcome="failed"`` marks a recovery
        whose restarted attempt died before its first report (the next
        recovery's trace then covers the follow-up)."""
        from ray_tpu._private import events as _events
        from ray_tpu.util import tracing

        # The flight event goes out unconditionally (BEFORE the tracing
        # gate): recovery cause + outcome must reach the recorder even
        # with span tracing off.
        cause = self.cause_event
        if not cause and self.cause == PREEMPTION:
            cause = _events.latest_event_id(["preempt.notice"])
        _events.emit("train.recovery", cause=cause,
                     subject={"run": self.run},
                     recovery_cause=self.cause, attempt=self.attempt,
                     outcome=outcome, recovery_s=float(recovery_s))
        if not tracing.enabled():
            return ""
        rid = tracing.gen_id()
        tracing.emit_span(
            "train.recovery", trace_id=self.trace_id, ts=self.t0_wall,
            dur=recovery_s, span_id=rid,
            parent_span_id=self.parent_span_id, kind="train",
            run=self.run, cause=self.cause, attempt=self.attempt,
            outcome=outcome)
        cursor, used = self.t0_wall, 0.0
        for name, dur in self.phases:
            dur = min(dur, max(recovery_s - used, 0.0))
            tracing.emit_span(
                f"train.recovery.{name}", trace_id=self.trace_id,
                ts=cursor, dur=dur, parent_span_id=rid, kind="train",
                run=self.run)
            cursor += dur
            used += dur
        tracing.emit_span(
            "train.recovery.restore_first_step", trace_id=self.trace_id,
            ts=cursor, dur=max(recovery_s - used, 0.0),
            parent_span_id=rid, kind="train", run=self.run)
        return rid


class ResizeGuard:
    """Controller-side latch for resize/grow hints on the preempt channel.

    Unlike the training-loop :class:`~ray_tpu.checkpoint.preempt.
    PreemptionGuard` (which drives just-in-time saves), this guard only
    *observes*: ``target`` is the most recent explicit world-target ask,
    ``grow_hint`` flips when the GCS reports the cluster grew (so the
    controller re-evaluates feasibility immediately instead of waiting
    for its periodic grow check)."""

    def __init__(self, gcs_address: Optional[str] = None):
        from ray_tpu.checkpoint import preempt

        self._lock = threading.Lock()
        self._target: Optional[int] = None
        self._grow_hint = False

        def on_notice(notice: Dict[str, Any]) -> None:
            wt = notice.get("world_target")
            with self._lock:
                if wt is not None:
                    self._target = int(wt)
                elif notice.get("kind") == "capacity":
                    self._grow_hint = True

        self._cb = preempt.register_preempt_callback(on_notice)
        preempt.ensure_listener(gcs_address)

    @property
    def target(self) -> Optional[int]:
        with self._lock:
            return self._target

    def take_grow_hint(self) -> bool:
        with self._lock:
            hint, self._grow_hint = self._grow_hint, False
            return hint

    def clear_target(self, applied: Optional[int] = None) -> None:
        """Drop the latched target once an attempt runs at it (a *newer*
        ask that raced in stays latched)."""
        with self._lock:
            if applied is None or self._target == applied:
                self._target = None

    def close(self) -> None:
        from ray_tpu.checkpoint import preempt

        preempt.unregister_preempt_callback(self._cb)

    def __enter__(self) -> "ResizeGuard":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
