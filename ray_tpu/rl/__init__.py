"""ray_tpu.rl: the RL post-training loop — serve ↔ train weight sync.

Three legs (ROADMAP item 1): the weight-sync plane
(:mod:`ray_tpu.rl.weight_sync` — versioned crc32 manifests over
compiled-DAG channels, 2PC checkpoint fallback), the rollout scheduler
(:mod:`ray_tpu.rl.rollout` — generation/learner phases on one chip pool,
staleness first-class), and the experience path
(:mod:`ray_tpu.rl.experience` — engine logprobs into LearnerGroup-shaped
batches, token-level PPO). The generator side lives on
``ContinuousLlamaDeployment.swap_weights`` / ``enable_weight_sync``
(tick-boundary swap) and ``ContinuousBatcher.swap_params``.
"""

from ray_tpu.rl.experience import (ExperienceBuffer, SequenceRecord,
                                   TokenPPOLearner)
from ray_tpu.rl.rollout import RolloutScheduler
from ray_tpu.rl.weight_sync import (RL_KV_NS, WeightPublisher,
                                    WeightSubscriber, WeightSyncError,
                                    build_manifest, latest_manifest,
                                    verify_manifest)

__all__ = [
    "ExperienceBuffer", "SequenceRecord", "TokenPPOLearner",
    "RolloutScheduler", "RL_KV_NS", "WeightPublisher", "WeightSubscriber",
    "WeightSyncError", "build_manifest", "latest_manifest",
    "verify_manifest",
]
