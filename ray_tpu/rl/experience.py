"""RL experience path: engine logprobs → LearnerGroup-compatible batches.

Per-token behavior logprobs come from the serving engine itself
(``ContinuousBatcher.score_logprobs`` — the same params and forward that
generated the tokens), so the learner's importance ratios are against the
TRUE behavior policy, tagged with the weight version that produced each
sequence. :class:`ExperienceBuffer` accumulates sequences and emits the
``[T, N]``-layout trajectory dicts ``LearnerGroup._shard`` already knows
how to shard (env axis 1; every array [T, N] or [1, N]).

:class:`TokenPPOLearner` closes the loop: a token-level PPO update over a
toy llama policy, exposing the ``compute_gradients`` / ``apply_gradients``
/ ``get_weights`` / ``set_weights`` quartet so it drops into
``_LearnerActor`` and the LearnerGroup's bucketed-flat allreduce unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from ray_tpu._private import xla_monitor


class SequenceRecord(NamedTuple):
    """One generated sequence with everything the learner needs."""

    prompt: List[int]
    tokens: List[int]          # generated tokens
    logprobs: np.ndarray       # behavior per-token logprobs, len(tokens)
    reward: float              # terminal scalar reward
    weight_version: int        # generator version that produced it
    staleness: int             # trainer_version - weight_version at collect


class ExperienceBuffer:
    """Accumulates :class:`SequenceRecord`\\ s and packs them into the
    ``[T, N]`` trajectory-dict layout (sequences along axis 1, token
    positions along axis 0, right-padded with a mask)."""

    def __init__(self, gamma: float = 1.0):
        self.gamma = float(gamma)
        self._records: List[SequenceRecord] = []

    def add(self, record: SequenceRecord) -> None:
        self._records.append(record)

    def __len__(self) -> int:
        return len(self._records)

    def staleness(self) -> List[int]:
        return [r.staleness for r in self._records]

    def clear(self) -> None:
        self._records = []

    def to_batch(self, max_len: Optional[int] = None
                 ) -> Dict[str, np.ndarray]:
        """Pack to one trajectory dict. Shapes: ``tokens_full`` [S, N]
        (prompt + generation, right-padded), ``actions`` /
        ``behavior_logp`` / ``advantages`` / ``mask`` [T, N] over the
        generated positions, ``prompt_len`` / ``weight_version`` /
        ``staleness`` [1, N] (kept 2-D so the shard slice ``v[:, lo:hi]``
        applies uniformly). Advantages are the reward broadcast over the
        sequence's tokens, whitened across the batch."""
        recs = self._records
        if not recs:
            raise ValueError("experience buffer is empty")
        N = len(recs)
        T = max(len(r.tokens) for r in recs)
        S = max(len(r.prompt) + len(r.tokens) for r in recs)
        if max_len is not None:
            S = max(S, int(max_len))
        tokens_full = np.zeros((S, N), np.int32)
        actions = np.zeros((T, N), np.int32)
        behavior_logp = np.zeros((T, N), np.float32)
        mask = np.zeros((T, N), np.float32)
        rewards = np.zeros((N,), np.float32)
        prompt_len = np.zeros((1, N), np.int32)
        version = np.zeros((1, N), np.int32)
        staleness = np.zeros((1, N), np.int32)
        for n, r in enumerate(recs):
            full = list(r.prompt) + list(r.tokens)
            tokens_full[:len(full), n] = full
            t = len(r.tokens)
            actions[:t, n] = r.tokens
            behavior_logp[:t, n] = np.asarray(r.logprobs, np.float32)
            mask[:t, n] = 1.0
            rewards[n] = r.reward
            prompt_len[0, n] = len(r.prompt)
            version[0, n] = r.weight_version
            staleness[0, n] = r.staleness
        adv = rewards - rewards.mean()
        std = rewards.std()
        if std > 1e-6:
            adv = adv / std
        advantages = (adv[None, :] * mask).astype(np.float32)
        return {
            "tokens_full": tokens_full,
            "actions": actions,
            "behavior_logp": behavior_logp,
            "advantages": advantages,
            "mask": mask,
            "prompt_len": prompt_len,
            "weight_version": version,
            "staleness": staleness,
        }


class TokenPPOLearner:
    """Token-level PPO over a llama policy (the generator's own weights).

    The clipped surrogate runs per generated token against the engine's
    behavior logprobs; ``rho_clip`` additionally caps the importance
    ratio IMPALA/APPO-style, bounding the correction applied to stale
    (off-policy) sequences collected under an older weight version.
    """

    def __init__(self, config: Any, params: Any = None, lr: float = 1e-3,
                 clip: float = 0.2, rho_clip: Optional[float] = None,
                 entropy_coeff: float = 0.0, seed: int = 0):
        import jax
        import optax

        from ray_tpu.models import llama

        self.config = config
        self.optimizer = optax.adam(lr)
        if params is None:
            params = llama.init_params(config, jax.random.PRNGKey(seed))
        self.params = params
        self.opt_state = self.optimizer.init(self.params)
        clip_c, rho_c, ent_c = clip, rho_clip, entropy_coeff
        cfg = config

        def loss_fn(params, b):
            import jax.numpy as jnp

            # Teacher-forced forward over the full padded sequences:
            # logits at position s predict the token at s+1, so the
            # generated token t of sequence n is scored by the logits row
            # at prompt_len[n] - 1 + t.
            logits = llama.forward(params, b["tokens_full"].T, cfg)
            logp_all = jax.nn.log_softmax(logits)          # [N, S, V]
            T = b["actions"].shape[0]
            pos = (b["prompt_len"][0][:, None] - 1
                   + jnp.arange(T)[None, :])               # [N, T]
            rows = jnp.take_along_axis(
                logp_all, pos[:, :, None],
                axis=1)                                    # [N, T, V]
            logp = jnp.take_along_axis(
                rows, b["actions"].T[:, :, None],
                axis=2)[:, :, 0].T                         # [T, N]
            ratio = jnp.exp(logp - b["behavior_logp"])
            if rho_c is not None:
                # Off-policy staleness correction: V-trace-style rho cap
                # on top of PPO's two-sided clip.
                ratio = jnp.minimum(ratio, rho_c)
            adv = b["advantages"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1.0 - clip_c, 1.0 + clip_c) * adv)
            denom = jnp.maximum(b["mask"].sum(), 1.0)
            pg_loss = -(surr * b["mask"]).sum() / denom
            entropy = -((jnp.exp(rows) * rows).sum(-1).T
                        * b["mask"]).sum() / denom
            total = pg_loss - ent_c * entropy
            return total, {"policy_loss": pg_loss, "entropy": entropy,
                           "mean_ratio": (ratio * b["mask"]).sum() / denom}

        self._grad_fn = xla_monitor.instrument(
            lambda p, b: jax.value_and_grad(loss_fn, has_aux=True)(p, b),
            name="rl_ppo_grad")

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return optax.apply_updates(params, updates), opt_state

        self._apply_fn = xla_monitor.instrument(apply_fn,
                                                name="rl_ppo_apply")

    @staticmethod
    def _to_device(batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax.numpy as jnp

        return {k: jnp.asarray(v) for k, v in batch.items()
                if k not in ("weight_version", "staleness")}

    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        (loss, metrics), grads = self._grad_fn(self.params,
                                               self._to_device(batch))
        metrics = {k: float(v) for k, v in metrics.items()}
        metrics["total_loss"] = float(loss)
        return grads, metrics

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads)

    def update_from_batch(self, batch) -> Dict[str, float]:
        grads, metrics = self.compute_gradients(batch)
        self.apply_gradients(grads)
        return metrics

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, weights)


__all__ = ["SequenceRecord", "ExperienceBuffer", "TokenPPOLearner"]
