"""RL rollout scheduler: generation and learner steps on one chip pool.

Generation and the learner update alternate as *phases*; on a shared chip
pool each learner phase rides a short-deadline arbiter lease
(``ChipPoolArbiter.request_handoff`` serve→train, lease_s = the phase
deadline) so the chips flow back to serving the moment the update lands —
the PR 15 ledger keeps the handoff crash-safe. Without an arbiter (single
host, tests) the phases still alternate; only the lease hop is skipped.

Every generated sequence is tagged with the weight version the generator
replica held when it produced it. Staleness (trainer version minus
sequence version) is first-class: the ``ray_tpu_rl_rollout_staleness``
gauge tracks it live, and ``staleness_clip`` drops sequences beyond the
clip from the batch (the learner additionally rho-clips what remains —
the IMPALA/APPO off-policy correction).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.rl.experience import ExperienceBuffer, SequenceRecord

logger = logging.getLogger(__name__)


class RolloutScheduler:
    """Drives generate → score → train rounds against a live generator.

    ``generate_fn(prompt_tokens, max_new_tokens) -> (tokens, logprobs,
    weight_version)`` is the generation hop — typically a closure over a
    serve handle or a local engine. ``trainer_version_fn`` reports the
    trainer's current published version (the staleness reference).
    """

    def __init__(self, generate_fn: Callable,
                 trainer_version_fn: Callable[[], int],
                 run: str = "rl",
                 staleness_clip: Optional[int] = None,
                 arbiter: Any = None,
                 learner_chips: int = 1,
                 lease_s: float = 5.0,
                 gamma: float = 1.0):
        self.generate = generate_fn
        self.trainer_version = trainer_version_fn
        self.run = run
        self.staleness_clip = staleness_clip
        self.arbiter = arbiter
        self.learner_chips = max(int(learner_chips), 1)
        self.lease_s = float(lease_s)
        self.buffer = ExperienceBuffer(gamma=gamma)
        self.dropped_stale = 0
        self._mtags = {"run": run}

    # -------------------------------------------------- generation phase
    def collect(self, prompts: Sequence[Sequence[int]],
                max_new_tokens: int,
                reward_fn: Callable[[List[int], List[int]], float],
                cause: str = "") -> int:
        """One generation phase: batch ``prompts`` through the engine,
        score each completed sequence with ``reward_fn(prompt, tokens)``,
        tag with version + staleness, and admit to the buffer. Returns
        the number of sequences admitted (stale-clipped ones are counted
        in ``dropped_stale``, not admitted)."""
        from ray_tpu._private import events as _events
        from ray_tpu._private import metrics_defs as mdefs

        trainer_v = int(self.trainer_version())
        admitted = 0
        worst_staleness = 0
        for prompt in prompts:
            tokens, logprobs, version = self.generate(
                list(prompt), max_new_tokens)
            staleness = max(trainer_v - int(version), 0)
            worst_staleness = max(worst_staleness, staleness)
            if self.staleness_clip is not None \
                    and staleness > self.staleness_clip:
                self.dropped_stale += 1
                _events.emit("rl.rollout_clip", cause=cause,
                             subject={"run": self.run},
                             version=int(version), trainer_version=trainer_v,
                             staleness=staleness)
                continue
            self.buffer.add(SequenceRecord(
                prompt=list(prompt), tokens=list(tokens),
                logprobs=logprobs, reward=float(reward_fn(list(prompt),
                                                          list(tokens))),
                weight_version=int(version), staleness=staleness))
            admitted += 1
        mdefs.RL_ROLLOUT_STALENESS.set(worst_staleness, tags=self._mtags)
        return admitted

    # ----------------------------------------------------- learner phase
    def learner_phase(self, fn: Callable[[], Any], cause: str = "") -> Any:
        """Run one learner step under a short-deadline chip lease when an
        arbiter co-schedules this pool (serve donates, the lease deadline
        returns the chips); plain call otherwise."""
        from ray_tpu._private import events as _events

        lease_id = ""
        if self.arbiter is not None:
            try:
                lease_id = self.arbiter.request_handoff(
                    "serve", self.learner_chips, lease_s=self.lease_s)
            except Exception:  # noqa: BLE001 — degraded: run unleased
                logger.exception("rl: learner-phase lease failed; "
                                 "running without a handoff")
        event_id = _events.emit(
            "rl.learner_phase", cause=cause,
            subject={"run": self.run, "lease_id": lease_id})
        t0 = time.perf_counter()
        try:
            return fn()
        finally:
            _events.emit("rl.learner_phase_done", cause=event_id,
                         subject={"run": self.run, "lease_id": lease_id},
                         seconds=round(time.perf_counter() - t0, 6))

    def drain_batch(self, max_len: Optional[int] = None
                    ) -> Dict[str, Any]:
        """Pop the accumulated experience as one [T, N] trajectory dict."""
        batch = self.buffer.to_batch(max_len=max_len)
        self.buffer.clear()
        return batch


__all__ = ["RolloutScheduler"]
