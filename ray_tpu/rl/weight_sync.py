"""RL weight-sync plane: trainer mesh → live generator replicas.

The trainer publishes a versioned :class:`WeightManifest` (per-leaf spec +
crc32, mirrored into the GCS ``__rl__`` KV namespace) after every N
optimizer steps; each generator replica holds a :class:`WeightSubscriber`
that streams the full host-side param pytree over a compiled-DAG shared
memory channel (``ray_tpu/experimental/channel.py``) and re-shards on
arrival with ``jax.device_put`` — the same elastic-reassembly contract as
the checkpoint plane's ``restore(target_shardings)``. When the fast path is
unavailable (channel gone, crc mismatch, publisher dead) the subscriber
falls back to the crc32-verified 2PC checkpoint manifest the publisher
wrote alongside, so fast path ≡ slow path bit-for-bit.

Backpressure is the channel's single-in-flight protocol: a publish blocks
until every subscriber acked the previous version, and past
``publish_timeout_s`` the publish SHEDS — with attribution, naming the
lagging reader indices read straight from the channel header — rather than
stalling the optimizer or buffering unboundedly (the PR 18
shed-with-attribution pattern).
"""

from __future__ import annotations

import json
import logging
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# Reserved GCS KV namespace mirroring the manifest chain (keys
# ``<run>/manifest/<version>`` + ``<run>/latest``), so any process with a
# cluster connection can answer "what weight version is current for run X"
# without holding the channel.
RL_KV_NS = "__rl__"

DEFAULT_CHANNEL_CAPACITY = 64 << 20  # params ride as one pickled payload


class WeightSyncError(RuntimeError):
    """A received payload failed manifest verification (crc/leaf-count)."""


def _kv():
    """The cluster KV when this process is connected, else ``None``
    (the checkpoint plane's idiom: KV mirroring is an accelerant, never a
    requirement)."""
    try:
        from ray_tpu._private import worker as worker_mod

        if worker_mod.global_worker_or_none() is None:
            return None
        from ray_tpu.experimental import internal_kv

        return internal_kv
    except Exception:  # noqa: BLE001 — no runtime in this process
        return None


def _host_leaves(params: Any) -> Tuple[List[np.ndarray], Any]:
    """Flatten to host numpy leaves + treedef (deterministic jax order —
    the crc32 manifest indexes leaves by this order on both sides)."""
    import jax

    leaves, treedef = jax.tree.flatten(params)
    return [np.asarray(leaf) for leaf in leaves], treedef


def build_manifest(run: str, version: int, step: int,
                   leaves: List[np.ndarray],
                   ckpt_root: Optional[str] = None,
                   ckpt_run: Optional[str] = None) -> Dict[str, Any]:
    """Versioned weight manifest: per-leaf shape/dtype/crc32 + the slow
    path pointer (checkpoint plane root/run) the fallback ladder ends at."""
    return {
        "run": run,
        "version": int(version),
        "step": int(step),
        "ts": time.time(),
        "leaves": [{
            "shape": list(a.shape),
            "dtype": str(a.dtype),
            "crc32": zlib.crc32(np.ascontiguousarray(a).tobytes()),
        } for a in leaves],
        "bytes": int(sum(a.nbytes for a in leaves)),
        "ckpt_root": ckpt_root,
        "ckpt_run": ckpt_run,
    }


def verify_manifest(manifest: Dict[str, Any],
                    leaves: List[np.ndarray]) -> None:
    """Integrity gate on arrival: leaf count + per-leaf crc32 against the
    manifest. Raises :class:`WeightSyncError` — the caller's cue to drop
    the payload and take the checkpoint fallback."""
    specs = manifest.get("leaves", [])
    if len(specs) != len(leaves):
        raise WeightSyncError(
            f"weight payload has {len(leaves)} leaves but manifest "
            f"v{manifest.get('version')} declares {len(specs)}")
    for i, (spec, leaf) in enumerate(zip(specs, leaves)):
        crc = zlib.crc32(np.ascontiguousarray(leaf).tobytes())
        if crc != spec["crc32"]:
            raise WeightSyncError(
                f"leaf {i} crc mismatch for weight version "
                f"{manifest.get('version')}: got {crc:#010x}, manifest "
                f"says {spec['crc32']:#010x}")


def _kv_put_manifest(manifest: Dict[str, Any]) -> None:
    kv = _kv()
    if kv is None:
        return
    try:
        run, version = manifest["run"], manifest["version"]
        raw = json.dumps(manifest).encode()
        kv.internal_kv_put(f"{run}/manifest/{version:010d}", raw,
                           overwrite=True, namespace=RL_KV_NS)
        kv.internal_kv_put(f"{run}/latest", raw, overwrite=True,
                           namespace=RL_KV_NS)
    except Exception:  # noqa: BLE001 — mirroring is best-effort
        logger.debug("rl: KV manifest mirror failed", exc_info=True)


def latest_manifest(run: str) -> Optional[Dict[str, Any]]:
    """Newest published manifest for ``run`` from the ``__rl__`` KV
    mirror (``None`` with no cluster or no publish yet)."""
    kv = _kv()
    if kv is None:
        return None
    try:
        raw = kv.internal_kv_get(f"{run}/latest", namespace=RL_KV_NS)
        return json.loads(raw) if raw else None
    except Exception:  # noqa: BLE001
        return None


class WeightPublisher:
    """Trainer-side half of the sync plane.

    Owns the channel (created eagerly so subscriber attach-specs exist
    before the first publish) and the version counter. ``publish_every``
    turns :meth:`maybe_publish` into the "every N optimizer steps" gate;
    ``ckpt_plane`` (a ``CheckpointPlane``) makes every publish also write
    the 2PC checkpoint manifest that backs the slow path — and the
    fast ≡ slow bit-identity acceptance check.
    """

    def __init__(self, run: str = "rl", n_subscribers: int = 1,
                 capacity: int = DEFAULT_CHANNEL_CAPACITY,
                 publish_every: int = 1,
                 publish_timeout_s: float = 5.0,
                 ckpt_plane: Any = None):
        from ray_tpu.experimental.channel import Channel

        self.run = run
        self.publish_every = max(int(publish_every), 1)
        self.publish_timeout_s = float(publish_timeout_s)
        self.ckpt_plane = ckpt_plane
        self.version = 0
        self._steps_since = 0
        self._chan = Channel(capacity=capacity, n_readers=n_subscribers)
        self._mtags = {"run": run}

    def subscriber_spec(self, idx: int):
        """Picklable attach-spec for subscriber ``idx`` — ship it into
        the generator replica (actor init kwarg / method arg) and hand it
        to :class:`WeightSubscriber`."""
        return self._chan.reader(idx)

    def maybe_publish(self, params: Any, step: int,
                      cause: str = "") -> Optional[Dict[str, Any]]:
        """Publish iff ``publish_every`` optimizer steps elapsed since
        the last publish. Returns the manifest when one went out."""
        self._steps_since += 1
        if self._steps_since < self.publish_every:
            return None
        self._steps_since = 0
        return self.publish(params, step, cause=cause)

    def publish(self, params: Any, step: int,
                cause: str = "") -> Dict[str, Any]:
        """Version, checksum, mirror, checkpoint, and push one weight
        snapshot. On subscriber backpressure past the timeout the publish
        is SHED (``manifest["shed"]`` lists the lagging reader indices)
        instead of blocking the optimizer."""
        from ray_tpu._private import events as _events
        from ray_tpu._private import metrics_defs as mdefs

        t0 = time.perf_counter()
        self.version += 1
        leaves, _treedef = _host_leaves(params)
        host_params = _host_tree(params)
        manifest = build_manifest(
            self.run, self.version, step, leaves,
            ckpt_root=getattr(self.ckpt_plane, "root", None),
            ckpt_run=getattr(self.ckpt_plane, "run", None))
        if self.ckpt_plane is not None:
            # Slow-path source of truth: the crc32-verified 2PC manifest
            # a cold-started or fallen-back replica restores from. Saved
            # BEFORE the channel push so a subscriber that misses the
            # fast path never sees a version without a checkpoint.
            self.ckpt_plane.save(self.version, host_params)
        _kv_put_manifest(manifest)
        event_id = _events.emit(
            "rl.manifest_publish", cause=cause,
            subject={"run": self.run},
            version=self.version, step=int(step),
            bytes=manifest["bytes"])
        manifest["event_id"] = event_id
        try:
            self._chan.write((manifest, host_params),
                             timeout=self.publish_timeout_s)
        except Exception as e:  # noqa: BLE001 — shed, don't stall training
            lagging = self.lagging_subscribers()
            manifest["shed"] = lagging or [-1]
            for idx in (lagging or [-1]):
                mdefs.RL_SYNC_SHED.inc(
                    tags={"run": self.run, "subscriber": str(idx)})
            _events.emit("rl.publish_shed", cause=event_id,
                         subject={"run": self.run},
                         version=self.version, lagging=str(lagging),
                         error=type(e).__name__)
            logger.warning(
                "rl: publish v%d shed (lagging subscribers %s): %s",
                self.version, lagging, e)
        else:
            mdefs.RL_SYNC_BYTES.inc(manifest["bytes"],
                                    tags={**self._mtags, "path": "publish"})
        mdefs.RL_SYNC_SECONDS.observe(time.perf_counter() - t0,
                                      tags={**self._mtags,
                                            "path": "publish"})
        mdefs.RL_VERSION.set(self.version,
                             tags={**self._mtags, "role": "trainer"})
        return manifest

    def lagging_subscribers(self) -> List[int]:
        """Subscriber indices that have not acked the latest channel
        version — the shed-attribution readback."""
        try:
            return self._chan.lagging_readers()
        except Exception:  # noqa: BLE001
            return []

    def close(self) -> None:
        try:
            self._chan.close()
        except Exception:  # noqa: BLE001
            pass

    def destroy(self) -> None:
        try:
            self._chan.destroy()
        except Exception:  # noqa: BLE001
            pass


def _host_tree(params: Any) -> Any:
    import jax

    return jax.tree.map(np.asarray, params)


class WeightSubscriber:
    """Generator-side half: non-blocking poll for the next published
    version, crc-verified, optionally re-sharded onto this replica's
    layout, with the checkpoint manifest as the fallback ladder's
    last rung."""

    def __init__(self, spec: Any, run: str = "rl",
                 target_shardings: Any = None):
        self.run = run
        self._chan = spec
        self._shardings = target_shardings
        self.version = 0
        self._mtags = {"run": run}

    def poll(self, timeout: float = 0.05
             ) -> Optional[Tuple[Dict[str, Any], Any]]:
        """One fast-path receive attempt. Returns ``(manifest, params)``
        when a fresh verified version arrived, ``None`` on timeout.
        Raises :class:`WeightSyncError` on verification failure and
        ``ChannelClosed`` when the publisher is gone — both are the
        caller's cue to fall back to :meth:`restore_fallback`."""
        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.experimental.channel import ChannelTimeout

        t0 = time.perf_counter()
        try:
            manifest, params = self._chan.read(timeout=timeout)
        except ChannelTimeout:
            return None
        leaves, _ = _host_leaves(params)
        verify_manifest(manifest, leaves)
        params = self._reshard(params)
        self.version = int(manifest["version"])
        mdefs.RL_SYNC_BYTES.inc(manifest["bytes"],
                                tags={**self._mtags, "path": "subscribe"})
        mdefs.RL_SYNC_SECONDS.observe(time.perf_counter() - t0,
                                      tags={**self._mtags,
                                            "path": "subscribe"})
        return manifest, params

    def restore_fallback(self, manifest: Optional[Dict[str, Any]] = None
                         ) -> Tuple[Dict[str, Any], Any]:
        """Slow path: restore the manifest's version from its 2PC
        checkpoint (``load_latest`` — crc32-verified, filesystem-only).
        With no manifest in hand, the ``__rl__`` KV mirror supplies the
        newest one."""
        from ray_tpu._private import metrics_defs as mdefs
        from ray_tpu.checkpoint import load_latest

        t0 = time.perf_counter()
        if manifest is None:
            manifest = latest_manifest(self.run)
        if not manifest or not manifest.get("ckpt_root"):
            raise WeightSyncError(
                f"no checkpoint fallback available for run {self.run!r} "
                f"(manifest={manifest})")
        params = load_latest(manifest["ckpt_root"],
                             run=manifest.get("ckpt_run"),
                             step=int(manifest["version"]))
        params = getattr(params, "params", params)
        leaves, _ = _host_leaves(params)
        verify_manifest(manifest, leaves)
        params = self._reshard(params)
        self.version = int(manifest["version"])
        mdefs.RL_SYNC_BYTES.inc(manifest["bytes"],
                                tags={**self._mtags, "path": "fallback"})
        mdefs.RL_SYNC_SECONDS.observe(time.perf_counter() - t0,
                                      tags={**self._mtags,
                                            "path": "fallback"})
        return manifest, params

    def _reshard(self, params: Any) -> Any:
        """Trainer layout → this replica's layout: ``jax.device_put``
        every leaf onto the target sharding (the checkpoint plane's
        elastic-reshard contract, applied to a live payload)."""
        if self._shardings is None:
            return params
        import jax

        leaves, treedef = jax.tree.flatten(params)
        shardings = jax.tree.flatten(self._shardings)[0]
        if len(shardings) != len(leaves):
            raise WeightSyncError(
                f"target shardings have {len(shardings)} leaves but the "
                f"payload has {len(leaves)}")
        return jax.tree.unflatten(
            treedef, [jax.device_put(a, s)
                      for a, s in zip(leaves, shardings)])


__all__ = [
    "RL_KV_NS", "WeightPublisher", "WeightSubscriber", "WeightSyncError",
    "build_manifest", "verify_manifest", "latest_manifest",
]
