"""Trial schedulers: FIFO, ASHA (async successive halving), median stopping.

Reference: ``python/ray/tune/schedulers`` — ``AsyncHyperBandScheduler``
(async_hyperband.py) promotes trials through rungs, stopping those below the
rung's top-1/reduction_factor quantile; ``MedianStoppingRule`` stops trials
whose best result is below the median of peers at the same step.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, step: int, value: float) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    """ASHA. ``time_attr`` steps are reported results (1-indexed)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung thresholds: milestones grace * rf^k up to max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.recorded: Dict[int, List[float]] = collections.defaultdict(list)

    def _better(self, a: float, b: float) -> bool:
        return a >= b if self.mode == "max" else a <= b

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        for rung in reversed(self.rungs):
            if step == rung:
                values = self.recorded[rung]
                values.append(value)
                if len(values) < self.rf:
                    return CONTINUE  # not enough peers yet: be permissive
                ordered = sorted(values, reverse=(self.mode == "max"))
                cutoff = ordered[max(len(ordered) // self.rf - 1, 0)]
                if not self._better(value, cutoff):
                    return STOP
        return CONTINUE


class MedianStoppingRule:
    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.best: Dict[str, float] = {}
        self.histories: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        self.histories[trial_id].append(value)
        if step <= self.grace:
            return CONTINUE
        peers = [max(h) if self.mode == "max" else min(h)
                 for tid, h in self.histories.items() if tid != trial_id]
        if len(peers) < self.min_samples:
            return CONTINUE
        peers_sorted = sorted(peers)
        median = peers_sorted[len(peers_sorted) // 2]
        mine = max(self.histories[trial_id]) if self.mode == "max" \
            else min(self.histories[trial_id])
        ok = mine >= median if self.mode == "max" else mine <= median
        return CONTINUE if ok else STOP


EXPLOIT = "EXPLOIT"


class PopulationBasedTraining:
    """PBT (reference: ``python/ray/tune/schedulers/pbt.py``): at every
    ``perturbation_interval`` reported steps, a trial in the bottom
    quantile clones the checkpoint + config of a random top-quantile peer
    and perturbs the mutated hyperparameters (exploit + explore). The
    controller performs the fork; this class decides who forks from whom
    and how configs mutate."""

    requires_checkpoints = True

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        assert mode in ("max", "min")
        assert 0.0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        import numpy as _np

        self._rng = _np.random.default_rng(seed)
        self._latest: Dict[str, float] = {}   # trial -> last metric value
        self._configs: Dict[str, Dict] = {}   # trial -> current config
        self.exploit_count = 0

    def on_trial_config(self, trial_id: str, config: Dict) -> None:
        self._configs[trial_id] = dict(config)

    def _quantiles(self):
        ordered = sorted(self._latest,
                         key=lambda t: self._latest[t],
                         reverse=(self.mode == "max"))
        k = max(1, int(len(ordered) * self.quantile))
        return ordered[:k], ordered[-k:]

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        self._latest[trial_id] = value
        if step % self.interval != 0 or len(self._latest) < 2:
            return CONTINUE
        top, bottom = self._quantiles()
        if trial_id in bottom and trial_id not in top:
            return EXPLOIT
        return CONTINUE

    def exploit(self, trial_id: str):
        """Pick a donor from the top quantile and build the perturbed
        config. Returns ``(donor_trial_id, new_config)``. Pure: the
        controller may still decline the fork (no donor checkpoint yet) —
        bookkeeping moves in :meth:`commit_exploit` once it commits."""
        top, _ = self._quantiles()
        donors = [t for t in top if t != trial_id]
        if not donors:
            return None, None
        donor = donors[int(self._rng.integers(0, len(donors)))]
        new_config = self._explore(dict(self._configs.get(donor, {})))
        return donor, new_config

    def commit_exploit(self, trial_id: str, new_config: Dict) -> None:
        """The controller actually forked ``trial_id`` onto ``new_config``."""
        self._configs[trial_id] = dict(new_config)
        self.exploit_count += 1

    def _explore(self, config: Dict) -> Dict:
        for key, domain in self.mutations.items():
            if callable(domain):
                resampled = domain()
            elif isinstance(domain, (list, tuple)):
                resampled = domain[int(self._rng.integers(0, len(domain)))]
            else:
                resampled = None
            cur = config.get(key)
            if resampled is not None and (
                    cur is None or self._rng.random() < self.resample_p):
                config[key] = resampled
            elif isinstance(cur, (int, float)) and \
                    not isinstance(cur, bool):
                factor = 1.2 if self._rng.random() < 0.5 else 0.8
                if isinstance(cur, int):
                    # Round, and keep positive ints from ratcheting to 0
                    # (int(1*0.8) would freeze a batch-size at 0 forever).
                    new = int(round(cur * factor))
                    config[key] = max(new, 1) if cur >= 1 else new
                else:
                    config[key] = cur * factor
            elif resampled is not None:
                config[key] = resampled
        return config
