"""Trial schedulers: FIFO, ASHA (async successive halving), median stopping.

Reference: ``python/ray/tune/schedulers`` — ``AsyncHyperBandScheduler``
(async_hyperband.py) promotes trials through rungs, stopping those below the
rung's top-1/reduction_factor quantile; ``MedianStoppingRule`` stops trials
whose best result is below the median of peers at the same step.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, step: int, value: float) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    """ASHA. ``time_attr`` steps are reported results (1-indexed)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung thresholds: milestones grace * rf^k up to max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.recorded: Dict[int, List[float]] = collections.defaultdict(list)

    def _better(self, a: float, b: float) -> bool:
        return a >= b if self.mode == "max" else a <= b

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        for rung in reversed(self.rungs):
            if step == rung:
                values = self.recorded[rung]
                values.append(value)
                if len(values) < self.rf:
                    return CONTINUE  # not enough peers yet: be permissive
                ordered = sorted(values, reverse=(self.mode == "max"))
                cutoff = ordered[max(len(ordered) // self.rf - 1, 0)]
                if not self._better(value, cutoff):
                    return STOP
        return CONTINUE


class MedianStoppingRule:
    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.best: Dict[str, float] = {}
        self.histories: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        self.histories[trial_id].append(value)
        if step <= self.grace:
            return CONTINUE
        peers = [max(h) if self.mode == "max" else min(h)
                 for tid, h in self.histories.items() if tid != trial_id]
        if len(peers) < self.min_samples:
            return CONTINUE
        peers_sorted = sorted(peers)
        median = peers_sorted[len(peers_sorted) // 2]
        mine = max(self.histories[trial_id]) if self.mode == "max" \
            else min(self.histories[trial_id])
        ok = mine >= median if self.mode == "max" else mine <= median
        return CONTINUE if ok else STOP


EXPLOIT = "EXPLOIT"


class PopulationBasedTraining:
    """PBT (reference: ``python/ray/tune/schedulers/pbt.py``): at every
    ``perturbation_interval`` reported steps, a trial in the bottom
    quantile clones the checkpoint + config of a random top-quantile peer
    and perturbs the mutated hyperparameters (exploit + explore). The
    controller performs the fork; this class decides who forks from whom
    and how configs mutate."""

    requires_checkpoints = True

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        assert mode in ("max", "min")
        assert 0.0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        import numpy as _np

        self._rng = _np.random.default_rng(seed)
        self._latest: Dict[str, float] = {}   # trial -> last metric value
        self._configs: Dict[str, Dict] = {}   # trial -> current config
        self.exploit_count = 0

    def on_trial_config(self, trial_id: str, config: Dict) -> None:
        self._configs[trial_id] = dict(config)

    def _quantiles(self):
        ordered = sorted(self._latest,
                         key=lambda t: self._latest[t],
                         reverse=(self.mode == "max"))
        k = max(1, int(len(ordered) * self.quantile))
        return ordered[:k], ordered[-k:]

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        self._latest[trial_id] = value
        if step % self.interval != 0 or len(self._latest) < 2:
            return CONTINUE
        top, bottom = self._quantiles()
        if trial_id in bottom and trial_id not in top:
            return EXPLOIT
        return CONTINUE

    def exploit(self, trial_id: str):
        """Pick a donor from the top quantile and build the perturbed
        config. Returns ``(donor_trial_id, new_config)``. Pure: the
        controller may still decline the fork (no donor checkpoint yet) —
        bookkeeping moves in :meth:`commit_exploit` once it commits."""
        top, _ = self._quantiles()
        donors = [t for t in top if t != trial_id]
        if not donors:
            return None, None
        donor = donors[int(self._rng.integers(0, len(donors)))]
        new_config = self._explore(dict(self._configs.get(donor, {})))
        return donor, new_config

    def commit_exploit(self, trial_id: str, new_config: Dict) -> None:
        """The controller actually forked ``trial_id`` onto ``new_config``."""
        self._configs[trial_id] = dict(new_config)
        self.exploit_count += 1

    def _explore(self, config: Dict) -> Dict:
        for key, domain in self.mutations.items():
            if callable(domain):
                resampled = domain()
            elif isinstance(domain, (list, tuple)):
                resampled = domain[int(self._rng.integers(0, len(domain)))]
            else:
                resampled = None
            cur = config.get(key)
            if resampled is not None and (
                    cur is None or self._rng.random() < self.resample_p):
                config[key] = resampled
            elif isinstance(cur, (int, float)) and \
                    not isinstance(cur, bool):
                factor = 1.2 if self._rng.random() < 0.5 else 0.8
                if isinstance(cur, int):
                    # Round, and keep positive ints from ratcheting to 0
                    # (int(1*0.8) would freeze a batch-size at 0 forever).
                    new = int(round(cur * factor))
                    config[key] = max(new, 1) if cur >= 1 else new
                else:
                    config[key] = cur * factor
            elif resampled is not None:
                config[key] = resampled
        return config


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: ``tune/schedulers/pb2.py`` —
    PBT whose explore step is a GP-bandit over continuous hyperparameters
    instead of random perturbation). Exploited trials pick their next
    hyperparameters by maximizing a GP-UCB acquisition fit on the
    population's observed (hyperparams -> score improvement) history, so
    the population steers toward productive regions with far fewer trials
    than random perturbation.

    ``hyperparam_bounds`` maps each tuned key to ``(low, high)``; values
    are modeled in normalized [0, 1] with an RBF-kernel GP (numpy-native —
    population histories are tiny, so exact GP inference is cheap).
    """

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_bounds: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.0,
                 n_candidates: int = 64,
                 seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds={key: (lo, hi)}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.ucb_kappa = ucb_kappa
        self.n_candidates = n_candidates
        self._prev_value: Dict[str, float] = {}
        # (normalized hyperparam vector, oriented score delta)
        self._observations: list = []
        self.MAX_OBS = 256

    # ------------------------------------------------------------ tracking
    def _normalize(self, config: Dict) -> Optional[list]:
        x = []
        for k, (lo, hi) in self.bounds.items():
            v = config.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                return None
            x.append((float(v) - lo) / (hi - lo) if hi > lo else 0.0)
        return x

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        prev = self._prev_value.get(trial_id)
        self._prev_value[trial_id] = value
        if prev is not None:
            x = self._normalize(self._configs.get(trial_id, {}))
            if x is not None:
                delta = value - prev
                if self.mode == "min":
                    delta = -delta
                self._observations.append((x, delta))
                del self._observations[:-self.MAX_OBS]
        return super().on_result(trial_id, step, value)

    def commit_exploit(self, trial_id: str, new_config: Dict) -> None:
        super().commit_exploit(trial_id, new_config)
        # The forked trial resumes from the DONOR's checkpointed score:
        # comparing its next report against the pre-fork value would
        # credit the checkpoint jump to the new hyperparameters and
        # poison the GP with a phantom improvement.
        self._prev_value.pop(trial_id, None)

    # ------------------------------------------------------------- explore
    def _explore(self, config: Dict) -> Dict:
        """GP-UCB selection over the bounded hyperparameters (replaces
        PBT's random perturbation)."""
        import numpy as np

        keys = list(self.bounds)
        if len(self._observations) < 4:
            # Cold start: uniform draw inside the bounds.
            for k in keys:
                lo, hi = self.bounds[k]
                config[k] = lo + (hi - lo) * float(self._rng.random())
            return config
        X = np.asarray([x for x, _ in self._observations])
        y = np.asarray([d for _, d in self._observations])
        y = (y - y.mean()) / (y.std() + 1e-9)

        def rbf(a, b, ls=0.2):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (ls * ls))

        K = rbf(X, X) + 1e-2 * np.eye(len(X))
        try:
            K_inv = np.linalg.inv(K)
        except np.linalg.LinAlgError:
            K_inv = np.linalg.pinv(K)
        cands = self._rng.random((self.n_candidates, len(keys)))
        Ks = rbf(cands, X)
        mu = Ks @ K_inv @ y
        var = np.clip(1.0 - np.einsum("ij,jk,ik->i", Ks, K_inv, Ks),
                      1e-9, None)
        best = cands[int(np.argmax(mu + self.ucb_kappa * np.sqrt(var)))]
        for k, u in zip(keys, best):
            lo, hi = self.bounds[k]
            config[k] = lo + (hi - lo) * float(u)
        return config
