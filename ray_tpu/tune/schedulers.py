"""Trial schedulers: FIFO, ASHA (async successive halving), median stopping.

Reference: ``python/ray/tune/schedulers`` — ``AsyncHyperBandScheduler``
(async_hyperband.py) promotes trials through rungs, stopping those below the
rung's top-1/reduction_factor quantile; ``MedianStoppingRule`` stops trials
whose best result is below the median of peers at the same step.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, step: int, value: float) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    """ASHA. ``time_attr`` steps are reported results (1-indexed)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung thresholds: milestones grace * rf^k up to max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.recorded: Dict[int, List[float]] = collections.defaultdict(list)

    def _better(self, a: float, b: float) -> bool:
        return a >= b if self.mode == "max" else a <= b

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        for rung in reversed(self.rungs):
            if step == rung:
                values = self.recorded[rung]
                values.append(value)
                if len(values) < self.rf:
                    return CONTINUE  # not enough peers yet: be permissive
                ordered = sorted(values, reverse=(self.mode == "max"))
                cutoff = ordered[max(len(ordered) // self.rf - 1, 0)]
                if not self._better(value, cutoff):
                    return STOP
        return CONTINUE


class MedianStoppingRule:
    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.best: Dict[str, float] = {}
        self.histories: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        self.histories[trial_id].append(value)
        if step <= self.grace:
            return CONTINUE
        peers = [max(h) if self.mode == "max" else min(h)
                 for tid, h in self.histories.items() if tid != trial_id]
        if len(peers) < self.min_samples:
            return CONTINUE
        peers_sorted = sorted(peers)
        median = peers_sorted[len(peers_sorted) // 2]
        mine = max(self.histories[trial_id]) if self.mode == "max" \
            else min(self.histories[trial_id])
        ok = mine >= median if self.mode == "max" else mine <= median
        return CONTINUE if ok else STOP
