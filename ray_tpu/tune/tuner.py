"""Tuner + TuneController: trial orchestration over actors.

Reference: ``python/ray/tune/tuner.py:44`` (Tuner) and
``tune/execution/tune_controller.py:68`` — the event loop that launches trial
actors up to the resource/concurrency budget, consumes their reported
results, feeds the scheduler (early stopping), and assembles a ResultGrid.
Trials here are actors running the user function in a thread with a report
queue (the same session shape as ray_tpu.train's workers).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import BasicVariantGenerator

_report_queue_var = threading.local()


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    """Report intermediate metrics from inside a trainable
    (reference: ``ray.tune.report`` / ``session.report``). ``checkpoint``
    is any picklable trial state; PBT forks trials from the donor's last
    reported checkpoint."""
    q = getattr(_report_queue_var, "queue", None)
    if q is None:
        raise RuntimeError("tune.report() called outside a trial")
    q.put({"metrics": dict(metrics), "checkpoint": checkpoint})


def get_checkpoint():
    """Checkpoint the trial was started from (None for a fresh start;
    reference: ``ray.tune.get_checkpoint``). PBT-forked trials resume from
    their donor's state through this."""
    return getattr(_report_queue_var, "checkpoint", None)


class _TrialActor:
    """Runs one trial function; polled for reports (max_concurrency=2)."""

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._error: Optional[str] = None
        self._final: Any = None

    def run(self, fn: Callable, config: Dict[str, Any], checkpoint=None):
        _report_queue_var.queue = self._q
        _report_queue_var.checkpoint = checkpoint
        try:
            self._final = fn(config)
            if isinstance(self._final, dict):
                self._q.put({"metrics": dict(self._final), "checkpoint": None})
            return self._final
        finally:
            self._done.set()

    def poll(self):
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                break
        return {"reports": out, "finished": self._done.is_set()}


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_seed: Optional[int] = None
    # Model-based searcher (e.g. search.TPESearch): proposes configs
    # sequentially from completed-trial scores instead of the upfront
    # random/grid expansion (reference: tune/search/ search algorithms).
    search_alg: Any = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Optional[Dict[str, Any]]
    metrics_history: List[Dict[str, Any]]
    error: Optional[str] = None
    stopped_early: bool = False

    @property
    def last_result(self):
        return self.metrics


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self):
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        pick = max if mode == "max" else min
        return pick(scored, key=lambda r: r.metrics[metric])


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None, _restore_state: Optional[dict] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config
        self._restore_state = _restore_state

    # -------------------------------------------------- experiment state
    # Reference: the experiment-state snapshot Tune writes to the run dir
    # (tune/execution/tune_controller.py checkpointing + Tuner.restore).
    STATE_FILE = "tuner_state.pkl"
    STATE_SNAPSHOT_PERIOD_S = 1.0

    def _experiment_dir(self) -> str:
        import os
        import tempfile

        storage = getattr(self.run_config, "storage_path", None) or             os.path.join(tempfile.gettempdir(), "ray_tpu_results")
        name = getattr(self.run_config, "name", None) or "tune_experiment"
        path = os.path.join(storage, name)
        os.makedirs(path, exist_ok=True)
        return path

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                resume_errored: bool = False) -> "Tuner":
        """Resume an interrupted/failed experiment from its state snapshot
        (reference: ``Tuner.restore(path, trainable)``). Unfinished trials
        continue from their last reported checkpoint; errored trials rerun
        from theirs when ``resume_errored``."""
        import os
        import pickle as _pickle

        with open(os.path.join(path, cls.STATE_FILE), "rb") as f:
            state = _pickle.load(f)
        state["resume_errored"] = resume_errored
        tuner = cls(trainable, param_space=state.get("param_space"),
                    tune_config=state.get("tune_config"),
                    run_config=state.get("run_config"),
                    _restore_state=state)
        return tuner

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        scheduler = tc.scheduler or sched_mod.FIFOScheduler()

        results: List[TrialResult] = []
        if self._restore_state is not None:
            state = self._restore_state
            resume_errored = state.get("resume_errored", False)
            pending = []
            for t in state["unfinished"]:
                pending.append((t["trial_id"], t["config"],
                                t.get("checkpoint")))
            for r in state["results"]:
                if r.error and resume_errored:
                    ckpt = state["checkpoints"].get(r.trial_id)
                    pending.append((r.trial_id, r.config, ckpt))
                else:
                    results.append(r)
            checkpoints: Dict[str, Any] = dict(state["checkpoints"])
            # A restored search_alg (pickled inside tune_config with its
            # observation history) keeps proposing the not-yet-run samples;
            # trials already proposed (finished or snapshotted as pending)
            # count toward num_samples.
            search_alg = tc.search_alg
            proposed = len(results) + len(pending)
        elif tc.search_alg is not None:
            search_alg = tc.search_alg
            pending = []  # proposed one at a time in the loop below
            checkpoints = {}
            proposed = 0
        else:
            generator = BasicVariantGenerator(tc.num_samples, tc.search_seed)
            configs = list(generator.variants(self.param_space))
            pending = [(f"trial_{i:05d}_{uuid.uuid4().hex[:6]}", cfg, None)
                       for i, cfg in enumerate(configs)]
            checkpoints = {}
            search_alg = None
            proposed = 0
        if search_alg is not None:
            # A restored run never reseeds: even with an empty completed
            # history the pre-crash RNG stream already produced the
            # snapshotted pending configs, and replaying it would duplicate
            # them.
            search_alg.configure(
                self.param_space, tc.metric, tc.mode,
                tc.search_seed if self._restore_state is None else None)
        limit = tc.max_concurrent_trials or max(len(pending), 1,
                                                4 if search_alg else 1)

        trial_cls = ray_tpu.remote(_TrialActor)
        running: Dict[str, Dict[str, Any]] = {}
        # checkpoints: last reported checkpoint per trial — PBT forks
        # bottom-quantile trials from a top-quantile donor's entry, and the
        # experiment-state snapshot persists them for Tuner.restore.
        is_pbt = getattr(scheduler, "requires_checkpoints", False)
        exp_dir = self._experiment_dir()
        last_snapshot = 0.0

        def snapshot_state(force=False):
            nonlocal last_snapshot
            if not force and \
                    time.monotonic() - last_snapshot < \
                    self.STATE_SNAPSHOT_PERIOD_S:
                return
            last_snapshot = time.monotonic()
            import cloudpickle as _cp
            import os

            state = {
                "param_space": self.param_space,
                "tune_config": tc,
                "run_config": self.run_config,
                "results": list(results),
                "unfinished": [
                    {"trial_id": tid, "config": st["config"],
                     "checkpoint": checkpoints.get(tid)}
                    for tid, st in running.items()
                ] + [{"trial_id": tid, "config": cfg, "checkpoint": ckpt}
                     for tid, cfg, ckpt in pending],
                "checkpoints": dict(checkpoints),
            }
            tmp = os.path.join(exp_dir, f".{self.STATE_FILE}.tmp")
            with open(tmp, "wb") as f:
                _cp.dump(state, f)
            os.replace(tmp, os.path.join(exp_dir, self.STATE_FILE))

        def launch(trial_id, cfg, checkpoint=None, st=None):
            actor = trial_cls.options(max_concurrency=2).remote()
            run_ref = actor.run.remote(self.trainable, cfg, checkpoint)
            if is_pbt:
                scheduler.on_trial_config(trial_id, cfg)
            if st is None:
                st = {"history": [], "steps": 0, "stopped": False}
            st.update(actor=actor, config=cfg, run_ref=run_ref)
            running[trial_id] = st

        def finish(tr: TrialResult):
            results.append(tr)
            if search_alg is not None and tr.metrics and tc.metric and \
                    tc.metric in tr.metrics:
                search_alg.on_trial_complete(tr.config,
                                             float(tr.metrics[tc.metric]))

        while pending or running or \
                (search_alg is not None and proposed < tc.num_samples):
            # Launch up to the concurrency limit.
            while pending and len(running) < limit:
                trial_id, cfg, ckpt = pending.pop(0)
                launch(trial_id, cfg, checkpoint=ckpt)
            while search_alg is not None and proposed < tc.num_samples \
                    and len(running) < limit:
                cfg = search_alg.suggest()
                launch(f"trial_{proposed:05d}_{uuid.uuid4().hex[:6]}", cfg)
                proposed += 1
            snapshot_state()
            # Poll every running trial.
            for trial_id, st in list(running.items()):
                try:
                    poll = ray_tpu.get(st["actor"].poll.remote(), timeout=30)
                except Exception as e:  # actor died
                    finish(TrialResult(
                        trial_id, st["config"],
                        st["history"][-1] if st["history"] else None,
                        st["history"], error=str(e)))
                    del running[trial_id]
                    continue
                stop = False
                exploit = False
                for r in poll["reports"]:
                    st["steps"] += 1
                    st["history"].append(r["metrics"])
                    if r.get("checkpoint") is not None:
                        checkpoints[trial_id] = r["checkpoint"]
                    if tc.metric and tc.metric in r["metrics"]:
                        verdict = scheduler.on_result(
                            trial_id, st["steps"],
                            float(r["metrics"][tc.metric]))
                        if verdict == sched_mod.STOP:
                            stop = True
                        elif verdict == getattr(sched_mod, "EXPLOIT", None):
                            exploit = True
                if exploit and not poll["finished"]:
                    donor, new_cfg = scheduler.exploit(trial_id)
                    if donor is not None and donor in checkpoints:
                        # Exploit+explore: replace this trial's actor with
                        # a clone of the donor's checkpoint under the
                        # perturbed config; history/steps continue.
                        ray_tpu.kill(st["actor"])
                        launch(trial_id, new_cfg,
                               checkpoint=checkpoints[donor], st=st)
                        scheduler.commit_exploit(trial_id, new_cfg)
                        continue
                if stop and not poll["finished"]:
                    ray_tpu.kill(st["actor"])
                    finish(TrialResult(
                        trial_id, st["config"],
                        st["history"][-1] if st["history"] else None,
                        st["history"], stopped_early=True))
                    del running[trial_id]
                    continue
                if poll["finished"]:
                    error = None
                    try:
                        ray_tpu.get(st["run_ref"], timeout=30)
                    except Exception as e:  # noqa: BLE001
                        error = str(e)
                    finish(TrialResult(
                        trial_id, st["config"],
                        st["history"][-1] if st["history"] else None,
                        st["history"], error=error))
                    ray_tpu.kill(st["actor"])
                    del running[trial_id]
            time.sleep(0.02)

        snapshot_state(force=True)
        return ResultGrid(results, tc.metric, tc.mode)


def run(trainable: Callable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None, mode: str = "max",
        scheduler=None, **_) -> ResultGrid:
    """``tune.run`` compatibility wrapper."""
    return Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler),
    ).fit()
