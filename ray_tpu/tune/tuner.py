"""Tuner + TuneController: trial orchestration over actors.

Reference: ``python/ray/tune/tuner.py:44`` (Tuner) and
``tune/execution/tune_controller.py:68`` — the event loop that launches trial
actors up to the resource/concurrency budget, consumes their reported
results, feeds the scheduler (early stopping), and assembles a ResultGrid.
Trials here are actors running the user function in a thread with a report
queue (the same session shape as ray_tpu.train's workers).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import BasicVariantGenerator

_report_queue_var = threading.local()


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    """Report intermediate metrics from inside a trainable
    (reference: ``ray.tune.report`` / ``session.report``). ``checkpoint``
    is any picklable trial state; PBT forks trials from the donor's last
    reported checkpoint."""
    q = getattr(_report_queue_var, "queue", None)
    if q is None:
        raise RuntimeError("tune.report() called outside a trial")
    q.put({"metrics": dict(metrics), "checkpoint": checkpoint})


def get_checkpoint():
    """Checkpoint the trial was started from (None for a fresh start;
    reference: ``ray.tune.get_checkpoint``). PBT-forked trials resume from
    their donor's state through this."""
    return getattr(_report_queue_var, "checkpoint", None)


class _TrialActor:
    """Runs one trial function; polled for reports (max_concurrency=2)."""

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._error: Optional[str] = None
        self._final: Any = None

    def run(self, fn: Callable, config: Dict[str, Any], checkpoint=None):
        _report_queue_var.queue = self._q
        _report_queue_var.checkpoint = checkpoint
        try:
            self._final = fn(config)
            if isinstance(self._final, dict):
                self._q.put({"metrics": dict(self._final), "checkpoint": None})
            return self._final
        finally:
            self._done.set()

    def poll(self):
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                break
        return {"reports": out, "finished": self._done.is_set()}


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_seed: Optional[int] = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Optional[Dict[str, Any]]
    metrics_history: List[Dict[str, Any]]
    error: Optional[str] = None
    stopped_early: bool = False

    @property
    def last_result(self):
        return self.metrics


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self):
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        pick = max if mode == "max" else min
        return pick(scored, key=lambda r: r.metrics[metric])


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        generator = BasicVariantGenerator(tc.num_samples, tc.search_seed)
        configs = list(generator.variants(self.param_space))
        scheduler = tc.scheduler or sched_mod.FIFOScheduler()
        limit = tc.max_concurrent_trials or len(configs)

        trial_cls = ray_tpu.remote(_TrialActor)
        pending = [(f"trial_{i:05d}_{uuid.uuid4().hex[:6]}", cfg)
                   for i, cfg in enumerate(configs)]
        running: Dict[str, Dict[str, Any]] = {}
        results: List[TrialResult] = []
        # Last reported checkpoint per trial — PBT forks bottom-quantile
        # trials from a top-quantile donor's entry (pbt.py exploit step).
        checkpoints: Dict[str, Any] = {}
        is_pbt = getattr(scheduler, "requires_checkpoints", False)

        def launch(trial_id, cfg, checkpoint=None, st=None):
            actor = trial_cls.options(max_concurrency=2).remote()
            run_ref = actor.run.remote(self.trainable, cfg, checkpoint)
            if is_pbt:
                scheduler.on_trial_config(trial_id, cfg)
            if st is None:
                st = {"history": [], "steps": 0, "stopped": False}
            st.update(actor=actor, config=cfg, run_ref=run_ref)
            running[trial_id] = st

        while pending or running:
            # Launch up to the concurrency limit.
            while pending and len(running) < limit:
                trial_id, cfg = pending.pop(0)
                launch(trial_id, cfg)
            # Poll every running trial.
            for trial_id, st in list(running.items()):
                try:
                    poll = ray_tpu.get(st["actor"].poll.remote(), timeout=30)
                except Exception as e:  # actor died
                    results.append(TrialResult(
                        trial_id, st["config"],
                        st["history"][-1] if st["history"] else None,
                        st["history"], error=str(e)))
                    del running[trial_id]
                    continue
                stop = False
                exploit = False
                for r in poll["reports"]:
                    st["steps"] += 1
                    st["history"].append(r["metrics"])
                    if r.get("checkpoint") is not None:
                        checkpoints[trial_id] = r["checkpoint"]
                    if tc.metric and tc.metric in r["metrics"]:
                        verdict = scheduler.on_result(
                            trial_id, st["steps"],
                            float(r["metrics"][tc.metric]))
                        if verdict == sched_mod.STOP:
                            stop = True
                        elif verdict == getattr(sched_mod, "EXPLOIT", None):
                            exploit = True
                if exploit and not poll["finished"]:
                    donor, new_cfg = scheduler.exploit(trial_id)
                    if donor is not None and donor in checkpoints:
                        # Exploit+explore: replace this trial's actor with
                        # a clone of the donor's checkpoint under the
                        # perturbed config; history/steps continue.
                        ray_tpu.kill(st["actor"])
                        launch(trial_id, new_cfg,
                               checkpoint=checkpoints[donor], st=st)
                        scheduler.commit_exploit(trial_id, new_cfg)
                        continue
                if stop and not poll["finished"]:
                    ray_tpu.kill(st["actor"])
                    results.append(TrialResult(
                        trial_id, st["config"],
                        st["history"][-1] if st["history"] else None,
                        st["history"], stopped_early=True))
                    del running[trial_id]
                    continue
                if poll["finished"]:
                    error = None
                    try:
                        ray_tpu.get(st["run_ref"], timeout=30)
                    except Exception as e:  # noqa: BLE001
                        error = str(e)
                    results.append(TrialResult(
                        trial_id, st["config"],
                        st["history"][-1] if st["history"] else None,
                        st["history"], error=error))
                    ray_tpu.kill(st["actor"])
                    del running[trial_id]
            time.sleep(0.02)

        return ResultGrid(results, tc.metric, tc.mode)


def run(trainable: Callable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None, mode: str = "max",
        scheduler=None, **_) -> ResultGrid:
    """``tune.run`` compatibility wrapper."""
    return Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler),
    ).fit()
