"""ray_tpu.tune: hyperparameter tuning (reference: ``python/ray/tune``)."""

from ray_tpu.tune.schedulers import (
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    Searcher,
    TPESearch,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.tuner import (
    ResultGrid,
    TrialResult,
    TuneConfig,
    Tuner,
    get_checkpoint,
    report,
    run,
)

__all__ = [
    "AsyncHyperBandScheduler", "FIFOScheduler", "MedianStoppingRule",
    "PB2", "PopulationBasedTraining", "ResultGrid", "Searcher",
    "TPESearch", "TrialResult", "TuneConfig", "Tuner", "choice",
    "get_checkpoint", "grid_search", "loguniform", "randint", "report",
    "run", "sample_from", "uniform",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("tune")
del _rlu
