"""ray_tpu.tune: hyperparameter tuning (reference: ``python/ray/tune``)."""

from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
)
from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.tuner import (
    ResultGrid,
    TrialResult,
    TuneConfig,
    Tuner,
    report,
    run,
)

__all__ = [
    "AsyncHyperBandScheduler", "FIFOScheduler", "MedianStoppingRule",
    "ResultGrid", "TrialResult", "TuneConfig", "Tuner", "choice",
    "grid_search", "loguniform", "randint", "report", "run", "sample_from",
    "uniform",
]
