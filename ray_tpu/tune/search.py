"""Search spaces + basic variant generation.

Reference: ``python/ray/tune/search`` — the sampling primitives
(``tune.choice/uniform/loguniform/randint``), ``tune.grid_search``, and the
``BasicVariantGenerator`` that expands grid axes into the cross product and
draws ``num_samples`` random samples of the remaining distributions.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def sample_from(fn: Callable[[Dict], Any]):
    return _SampleFrom(fn)


class _SampleFrom(Domain):
    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):
        return self.fn  # resolved after the rest of the config


class BasicVariantGenerator:
    """Cross product of grid axes × num_samples draws of distributions."""

    def __init__(self, num_samples: int = 1, seed: Optional[int] = None):
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self, param_space: Dict[str, Any]) -> Iterator[Dict[str, Any]]:
        grid_keys = [k for k, v in param_space.items()
                     if isinstance(v, GridSearch)]
        grids = [param_space[k].values for k in grid_keys]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg: Dict[str, Any] = {}
                for k, v in param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, _SampleFrom):
                        cfg[k] = None  # placeholder, resolved below
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                for k, v in param_space.items():
                    if isinstance(v, _SampleFrom):
                        cfg[k] = v.fn(cfg)
                yield cfg
