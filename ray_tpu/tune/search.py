"""Search spaces + basic variant generation.

Reference: ``python/ray/tune/search`` — the sampling primitives
(``tune.choice/uniform/loguniform/randint``), ``tune.grid_search``, and the
``BasicVariantGenerator`` that expands grid axes into the cross product and
draws ``num_samples`` random samples of the remaining distributions.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def sample_from(fn: Callable[[Dict], Any]):
    return _SampleFrom(fn)


class _SampleFrom(Domain):
    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):
        return self.fn  # resolved after the rest of the config


class Searcher:
    """Pluggable search-algorithm interface (reference:
    ``python/ray/tune/search/searcher.py`` Searcher ABC — BayesOpt /
    HyperOpt / Optuna all plug in through it). Implement these three
    methods and pass an instance as ``TuneConfig.search_alg``; the
    controller calls ``configure`` once with the resolved space, then
    alternates ``suggest`` / ``on_trial_complete``. Instances must be
    picklable: experiment restore resurrects the searcher WITH its
    observation history."""

    def configure(self, param_space: Dict[str, Any],
                  metric: Optional[str], mode: str,
                  seed: Optional[int] = None) -> None:
        raise NotImplementedError

    def suggest(self) -> Dict[str, Any]:
        """The next trial's config."""
        raise NotImplementedError

    def on_trial_complete(self, config: Dict[str, Any],
                          score: float) -> None:
        """Feed a finished trial's final RAW metric value back. The
        controller does NOT orient it: apply the ``mode`` received in
        :meth:`configure` yourself (min => lower is better)."""
        raise NotImplementedError


class TPESearch(Searcher):
    """Tree-structured Parzen Estimator search (model-based BayesOpt-class
    searcher; reference: ``python/ray/tune/search/`` hosts HyperOpt — whose
    core algorithm is TPE — plus BayesOpt/Optuna integrations. This build
    implements the algorithm natively on numpy instead of wrapping an
    external library).

    After ``n_startup`` random trials, observations are split at the
    ``gamma`` quantile into good/bad sets; numeric dimensions model each set
    with a Gaussian kernel density, categorical dimensions with smoothed
    counts, and each suggestion maximizes the acquisition l(x)/g(x) over
    ``n_candidates`` draws from the good model — the classic TPE rule.
    """

    def __init__(self, n_startup: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._space: Dict[str, Any] = {}
        self._metric: Optional[str] = None
        self._mode = "max"
        self._history: List[tuple] = []  # (config, score)

    def configure(self, param_space: Dict[str, Any], metric: Optional[str],
                  mode: str, seed: Optional[int] = None) -> None:
        if metric is None:
            raise ValueError(
                "TPESearch needs TuneConfig.metric set: without scores the "
                "model never trains and every trial would be a silent "
                "random draw")
        if any(isinstance(v, GridSearch) for v in param_space.values()):
            raise ValueError(
                "grid_search() dimensions are exhaustive by contract and "
                "a model-based searcher samples instead of enumerating — "
                "use tune.choice() for TPE-searchable categoricals, or "
                "drop search_alg to run the full grid")
        self._space = dict(param_space)
        self._metric = metric
        self._mode = mode
        # Seed only a fresh searcher: a restored one (non-empty history)
        # must keep its pickled RNG state or post-restore suggestions would
        # replay the pre-crash random stream and duplicate early trials.
        if seed is not None and not self._history:
            self.rng = random.Random(seed)

    # ------------------------------------------------------------ internals
    def _split(self):
        """(good, bad) configs, best-first by oriented score."""
        hist = sorted(self._history, key=lambda t: t[1],
                      reverse=(self._mode == "max"))
        n_good = max(1, int(len(hist) * self.gamma))
        return [c for c, _ in hist[:n_good]], [c for c, _ in hist[n_good:]]

    @staticmethod
    def _numeric_bounds(dom):
        if isinstance(dom, LogUniform):
            return dom.log_low, dom.log_high
        if isinstance(dom, (Uniform, RandInt)):
            return float(dom.low), float(dom.high)
        raise TypeError(dom)

    @staticmethod
    def _to_internal(dom, v):
        import math

        return math.log(v) if isinstance(dom, LogUniform) else float(v)

    @staticmethod
    def _from_internal(dom, x):
        import math

        lo, hi = TPESearch._numeric_bounds(dom)
        x = min(max(x, lo), hi)
        if isinstance(dom, LogUniform):
            return math.exp(x)
        if isinstance(dom, RandInt):
            return min(int(x), dom.high - 1)
        return x

    def _suggest_numeric(self, key, dom, good, bad):
        import math

        lo, hi = self._numeric_bounds(dom)
        span = hi - lo

        def pts(configs):
            return [self._to_internal(dom, c[key]) for c in configs
                    if key in c]

        gpts, bpts = pts(good), pts(bad)
        if not gpts:
            return None
        bw_g = max(span / math.sqrt(len(gpts) + 1), 1e-6 * span + 1e-12)
        bw_b = max(span / math.sqrt(len(bpts) + 1), 1e-6 * span + 1e-12)

        def kde(x, pts_, bw):
            if not pts_:
                return 1.0 / span if span else 1.0
            s = 0.0
            for p in pts_:
                z = (x - p) / bw
                s += math.exp(-0.5 * z * z)
            return s / (len(pts_) * bw) + 1e-12

        best_x, best_score = None, -1.0
        for _ in range(self.n_candidates):
            center = self.rng.choice(gpts)
            x = min(max(self.rng.gauss(center, bw_g), lo), hi)
            score = kde(x, gpts, bw_g) / kde(x, bpts, bw_b)
            if score > best_score:
                best_x, best_score = x, score
        return self._from_internal(dom, best_x)

    def _suggest_categorical(self, key, values, good, bad):
        def counts(configs):
            c = {v: 1.0 for v in map(_hashable, values)}  # +1 smoothing
            for cfg in configs:
                h = _hashable(cfg.get(key))
                if h in c:
                    c[h] += 1.0
            total = sum(c.values())
            return {v: n / total for v, n in c.items()}

        # l(v)/g(v) over the discrete support
        pg, pb = counts(good), counts(bad)
        best = max(values, key=lambda v: pg[_hashable(v)] / pb[_hashable(v)])
        return best

    # ------------------------------------------------------------ public
    def suggest(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        use_model = len(self._history) >= self.n_startup
        good, bad = self._split() if use_model else ([], [])
        for k, dom in self._space.items():
            if isinstance(dom, Categorical):
                cfg[k] = (self._suggest_categorical(k, dom.categories, good,
                                                    bad)
                          if use_model else dom.sample(self.rng))
            elif isinstance(dom, _SampleFrom):
                cfg[k] = None
            elif isinstance(dom, (Uniform, LogUniform, RandInt)):
                v = (self._suggest_numeric(k, dom, good, bad)
                     if use_model else None)
                cfg[k] = dom.sample(self.rng) if v is None else v
            elif isinstance(dom, Domain):
                cfg[k] = dom.sample(self.rng)
            else:
                cfg[k] = dom
        for k, dom in self._space.items():
            if isinstance(dom, _SampleFrom):
                cfg[k] = dom.fn(cfg)
        return cfg

    def on_trial_complete(self, config: Dict[str, Any],
                          score: float) -> None:
        self._history.append((dict(config), float(score)))


def _hashable(v):
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


class BasicVariantGenerator:
    """Cross product of grid axes × num_samples draws of distributions."""

    def __init__(self, num_samples: int = 1, seed: Optional[int] = None):
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self, param_space: Dict[str, Any]) -> Iterator[Dict[str, Any]]:
        grid_keys = [k for k, v in param_space.items()
                     if isinstance(v, GridSearch)]
        grids = [param_space[k].values for k in grid_keys]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg: Dict[str, Any] = {}
                for k, v in param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, _SampleFrom):
                        cfg[k] = None  # placeholder, resolved below
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                for k, v in param_space.items():
                    if isinstance(v, _SampleFrom):
                        cfg[k] = v.fn(cfg)
                yield cfg
