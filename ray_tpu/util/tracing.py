"""Distributed tracing: span propagation across task submissions.

Reference: ``python/ray/util/tracing/tracing_helper.py:326,446`` — the
reference wraps every task/actor submission and execution in
OpenTelemetry spans, propagating the trace context inside the TaskSpec so
a nested task graph yields one cross-process trace. This redesign keeps
the propagation protocol (trace_id + parent_span_id ride the TaskSpec)
but exports spans through the existing GCS task-event sink instead of an
OTel collector: ``ray-tpu timeline`` merges them into the chrome trace
with flow arrows linking parent and child spans across processes.

Off by default (``RAY_TPU_TRACING=1`` enables): the hot path pays only
one env check when disabled.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

_local = threading.local()
_reporter = None
_reporter_lock = threading.Lock()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_TRACING", "0") == "1"


def current() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the active span in this thread, if any."""
    return getattr(_local, "ctx", None)


def set_context(trace_id: str, span_id: str) -> None:
    _local.ctx = (trace_id, span_id)


def _live_core():
    """The current runtime, WITHOUT auto-initializing one (a flush thread
    must never resurrect a global worker after shutdown)."""
    from ray_tpu._private import worker as worker_mod

    w = getattr(worker_mod, "_global_worker", None)
    return None if w is None else w.core


def _get_reporter():
    global _reporter
    with _reporter_lock:
        if _reporter is None:
            from ray_tpu._private.events import BufferedPublisher

            def gcs_getter():
                core = _live_core()
                return getattr(core, "gcs", None) if core else None

            _reporter = BufferedPublisher("TASK_EVENT", gcs_getter)
        return _reporter


def _ids() -> str:
    return uuid.uuid4().hex[:16]


def gen_id() -> str:
    """A fresh 16-hex trace/span/request id (public: the serve plane
    mints request ids and pre-allocates span ids with it)."""
    return _ids()


def emit_span(name: str, *, trace_id: str, ts: float, dur: float,
              span_id: Optional[str] = None, parent_span_id: str = "",
              kind: str = "task", **attrs) -> str:
    """Record a span RETROSPECTIVELY with an explicit start/duration.

    The serve request path needs this because its phases are measured by
    bookkeeping (a request's queue wait ends when the admission loop
    picks it up, in a different thread than the one that submitted it),
    so a context manager around the work is impossible. Returns the span
    id ('' when tracing is disabled)."""
    if not enabled():
        return ""
    span_id = span_id or _ids()
    ids = _process_ids()
    _get_reporter().add({
        "state": "SPAN", "name": name, "kind": kind,
        "task_id": span_id,
        "trace_id": trace_id, "span_id": span_id,
        "parent_span_id": parent_span_id or "",
        "ts": ts, "dur": max(dur, 0.0), **ids, **attrs})
    return span_id


@contextmanager
def explicit_span(name: str, *, trace_id: str,
                  span_id: Optional[str] = None,
                  parent_span_id: str = "", kind: str = "task", **attrs):
    """Like :func:`span` but with a CALLER-CHOSEN span id, so the caller
    can hand that id to other processes as a parent BEFORE the span
    closes (the serve route span does this: engine lifecycle spans in
    the replica parent to it while the route call is still running).
    Sets the thread-local context so task submissions inside inherit
    the trace."""
    if not enabled():
        yield None
        return
    span_id = span_id or _ids()
    prev = current()
    set_context(trace_id, span_id)
    t0 = time.time()
    try:
        yield span_id
    finally:
        _local.ctx = prev
        ids = _process_ids()
        _get_reporter().add({
            "state": "SPAN", "name": name, "kind": kind,
            "task_id": span_id,
            "trace_id": trace_id, "span_id": span_id,
            "parent_span_id": parent_span_id or "",
            "ts": t0, "dur": time.time() - t0, **ids, **attrs})


@contextmanager
def span(name: str, kind: str = "task",
         trace_id: Optional[str] = None,
         parent_span_id: Optional[str] = None, **attrs):
    """Run a span: sets the thread-local context (children submitted
    inside inherit it) and records a SPAN task-event on exit. With no
    explicit trace context, continues the current one or starts fresh."""
    if not enabled():
        yield None
        return
    with _span_impl(name, kind=kind, trace_id=trace_id,
                    parent_span_id=parent_span_id, **attrs) as s:
        yield s


@contextmanager
def _span_impl(name: str, kind: str = "task",
               trace_id: Optional[str] = None,
               parent_span_id: Optional[str] = None, **attrs):
    prev = current()
    if trace_id is None:
        if prev is not None:
            trace_id, parent_span_id = prev
        else:
            trace_id = _ids()
    span_id = _ids()
    set_context(trace_id, span_id)
    t0 = time.time()
    try:
        yield span_id
    finally:
        _local.ctx = prev
        ids = _process_ids()
        _get_reporter().add({
            "state": "SPAN", "name": name, "kind": kind,
            "task_id": span_id,
            "trace_id": trace_id, "span_id": span_id,
            "parent_span_id": parent_span_id or "",
            "ts": t0, "dur": time.time() - t0, **ids, **attrs})


def _process_ids() -> Dict[str, str]:
    core = _live_core()
    if core is None:
        return {"worker_id": "driver", "node_id": ""}
    return {"worker_id": getattr(core, "worker_id", "driver")[:12],
            "node_id": str(getattr(core, "node_id", ""))[:12]}


def inject_context(spec) -> None:
    """Stamp the active trace context into a TaskSpec before submission
    (reference: _inject_tracing_into_function). Creates a submit span so
    the executor-side span parents to this submission."""
    if not (enabled() or current() is not None):
        return
    ctx = current()
    if ctx is None:
        trace_id, parent = _ids(), ""
    else:
        trace_id, parent = ctx
    submit_span = _ids()
    ids = _process_ids()
    _get_reporter().add({
        "state": "SPAN", "name": f"submit:{spec.name}", "kind": "submit",
        "task_id": submit_span,
        "trace_id": trace_id, "span_id": submit_span,
        "parent_span_id": parent, "ts": time.time(), "dur": 0.0, **ids})
    spec.trace_id = trace_id
    spec.parent_span_id = submit_span


@contextmanager
def execute_span(spec, kind: str = "task"):
    """Executor-side span for a pushed task, parented to the submitter's
    span carried in the spec (the cross-process edge)."""
    if not getattr(spec, "trace_id", ""):
        yield None
        return
    with _span_impl(spec.name, kind=kind, trace_id=spec.trace_id,
                    parent_span_id=spec.parent_span_id) as s:
        yield s


def spans_to_chrome_events(records: List[Dict[str, Any]]) \
        -> List[Dict[str, Any]]:
    """SPAN task-events -> chrome trace X events + flow arrows linking
    parent to child (visible as arrows across process rows in
    chrome://tracing / perfetto)."""
    by_id = {r["span_id"]: r for r in records}
    out: List[Dict[str, Any]] = []
    for r in records:
        out.append({
            "name": r["name"], "cat": f"span:{r.get('kind', 'task')}",
            "ph": "X", "ts": r["ts"] * 1e6,
            "dur": max(r.get("dur", 0.0), 1e-5) * 1e6,
            "pid": r.get("node_id", ""), "tid": r.get("worker_id", ""),
            "args": {"trace_id": r["trace_id"], "span_id": r["span_id"],
                     "parent_span_id": r.get("parent_span_id", "")},
        })
        parent = by_id.get(r.get("parent_span_id", ""))
        if parent is not None:
            mid = parent["ts"] + max(parent.get("dur", 0.0), 0.0) / 2
            out.append({"name": "trace", "cat": "flow", "ph": "s",
                        "id": r["span_id"], "ts": mid * 1e6,
                        "pid": parent.get("node_id", ""),
                        "tid": parent.get("worker_id", "")})
            out.append({"name": "trace", "cat": "flow", "ph": "f",
                        "bp": "e", "id": r["span_id"],
                        "ts": r["ts"] * 1e6,
                        "pid": r.get("node_id", ""),
                        "tid": r.get("worker_id", "")})
    return out


__all__ = ["enabled", "span", "execute_span", "inject_context",
           "current", "set_context", "spans_to_chrome_events",
           "gen_id", "emit_span", "explicit_span"]
