"""Scheduling strategies for tasks and actors.

Re-design of the reference strategy objects (reference:
``python/ray/util/scheduling_strategies.py``): plain dataclasses consumed by
the submit paths, which translate them into TaskSpec scheduling fields. The
node-side policies they select live in ``_private/scheduler/policies.py``
(hybrid/spread/affinity — reference ``raylet/scheduling/policy/``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    """Schedule onto a reserved placement-group bundle.

    Reference: ``scheduling_strategies.py`` PlacementGroupSchedulingStrategy.
    The task/actor charges the group's 2PC-reserved bundle resources instead
    of free node capacity, so gang placement survives contention.
    """

    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to one node. ``soft=True`` falls back to the default policy when
    the node is gone/full (reference: NodeAffinitySchedulingStrategy)."""

    node_id: str
    soft: bool = False


# String strategies "DEFAULT" (hybrid pack-then-spread) and "SPREAD"
# (min-utilization) are accepted anywhere a strategy object is.
SchedulingStrategyT = Optional[Any]
