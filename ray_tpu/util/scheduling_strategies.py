"""Scheduling strategies for tasks and actors.

Re-design of the reference strategy objects (reference:
``python/ray/util/scheduling_strategies.py``): plain dataclasses consumed by
the submit paths, which translate them into TaskSpec scheduling fields. The
node-side policies they select live in ``_private/scheduler/policies.py``
(hybrid/spread/affinity — reference ``raylet/scheduling/policy/``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    """Schedule onto a reserved placement-group bundle.

    Reference: ``scheduling_strategies.py`` PlacementGroupSchedulingStrategy.
    The task/actor charges the group's 2PC-reserved bundle resources instead
    of free node capacity, so gang placement survives contention.
    """

    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to one node. ``soft=True`` falls back to the default policy when
    the node is gone/full (reference: NodeAffinitySchedulingStrategy)."""

    node_id: str
    soft: bool = False


# Label-match operators (reference: ``ray.util.scheduling_strategies``
# In/NotIn/Exists/DoesNotExist). Each lowers to the JSON value spec carried
# in TaskSpec.label_selector and evaluated by the node-label policy
# (``_private/scheduler/policies.py::match_labels``).

def In(*values: str):
    """Label value must be one of ``values``."""
    return {"in": [str(v) for v in values]}


def NotIn(*values: str):
    """Label value must not be any of ``values`` (absent keys match)."""
    return {"not_in": [str(v) for v in values]}


def Exists():
    """Label key must be present (any value)."""
    return {"exists": True}


def DoesNotExist():
    """Label key must be absent."""
    return {"exists": False}


@dataclasses.dataclass
class NodeLabelSchedulingStrategy:
    """Constrain placement by node labels (reference:
    NodeLabelSchedulingStrategy + the node-label scheduling policy,
    ``raylet/scheduling/policy/node_label_scheduling_policy.h``).

    ``hard`` selectors must all match or the node is ineligible; ``soft``
    selectors rank eligible nodes (full-soft-match preferred). Values may be
    a plain string (exact match) or one of :func:`In`/:func:`NotIn`/
    :func:`Exists`/:func:`DoesNotExist`. TPU-native use: target one
    ICI-connected slice with ``hard={"tpu-slice": "slice-0"}``.
    """

    hard: Optional[dict] = None
    soft: Optional[dict] = None

    def encode(self) -> bytes:
        import json

        def norm(sel):
            out = {}
            for k, v in (sel or {}).items():
                out[k] = {"in": [str(v)]} if isinstance(v, str) else dict(v)
            return out

        return json.dumps(
            {"hard": norm(self.hard), "soft": norm(self.soft)},
            sort_keys=True).encode()


# String strategies "DEFAULT" (hybrid pack-then-spread) and "SPREAD"
# (min-utilization) are accepted anywhere a strategy object is.
SchedulingStrategyT = Optional[Any]
