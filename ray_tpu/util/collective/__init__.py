"""Host-tier collective communication (reference: ``python/ray/util/collective``)."""

from ray_tpu.util.collective.collective import (
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)

__all__ = [
    "allgather", "allreduce", "barrier", "broadcast",
    "destroy_collective_group", "get_collective_group_size", "get_rank",
    "init_collective_group", "is_group_initialized", "recv", "reduce",
    "reducescatter", "send",
]
