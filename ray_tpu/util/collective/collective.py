"""Collective groups for ray_tpu (reference: ``python/ray/util/collective/collective.py``).

Two tiers, mirroring the reference's NCCL/Gloo split but TPU-native:

* **Device tier** (inside ``jit``/``shard_map``): collectives are XLA ops over
  ICI — use :mod:`ray_tpu.parallel` meshes and ``jax.lax.psum/all_gather/...``
  directly. Nothing to "initialize"; the mesh is the group.
* **Host tier** (this module): CPU/numpy collectives between ray_tpu actors,
  the Gloo-equivalent (reference ``gloo_collective_group.py``). Rendezvous is a
  named store actor (reference ``nccl_collective_group.py:29``); data moves
  through the object store. Used for coordinator-style reductions (metrics,
  rendezvous, weight broadcast between actor groups), not the training hot path.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "product": lambda xs: np.prod(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
}


class _RendezvousStore:
    """Named actor used as the group rendezvous + data plane.

    One instance per collective group; ranks post numpy buffers keyed by
    (sequence-number, rank) and poll for peers' contributions.
    """

    def __init__(self, world_size: int):
        self._world_size = world_size
        self._buffers: Dict[str, Dict[int, object]] = {}
        self._arrived: Dict[str, set] = {}

    def put(self, seq: str, rank: int, value) -> None:
        self._buffers.setdefault(seq, {})[rank] = value

    def collect(self, seq: str, num: Optional[int] = None):
        want = num if num is not None else self._world_size
        bufs = self._buffers.get(seq, {})
        if len(bufs) < want:
            return None
        return [bufs[r] for r in sorted(bufs)]

    def arrive(self, seq: str, rank: int) -> int:
        self._arrived.setdefault(seq, set()).add(rank)
        return len(self._arrived[seq])

    def gc(self, seq: str) -> None:
        self._buffers.pop(seq, None)
        self._arrived.pop(seq, None)

    def world_size(self) -> int:
        return self._world_size


class CollectiveGroup:
    """Per-process handle to one collective group (one per rank)."""

    def __init__(self, name: str, world_size: int, rank: int, store):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._store = store
        self._seq = 0
        self._lock = threading.Lock()

    def _next_seq(self, op: str) -> str:
        with self._lock:
            self._seq += 1
            return f"{op}:{self._seq}"

    def _poll(self, fn, timeout_s: float = 120.0):
        deadline = time.monotonic() + timeout_s
        backoff = 0.0005
        while True:
            out = ray_tpu.get(fn())
            if out is not None:
                return out
            if time.monotonic() > deadline:
                raise TimeoutError(f"collective on group {self.name!r} timed out")
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.05)

    # -- ops ---------------------------------------------------------------
    def allreduce(self, tensor: np.ndarray, op: str = "sum") -> np.ndarray:
        parts = self.allgather(tensor)
        return _REDUCE_OPS[op](np.stack(parts))

    def allgather(self, tensor: np.ndarray) -> List[np.ndarray]:
        seq = self._next_seq("ag")
        ray_tpu.get(self._store.put.remote(seq, self.rank, np.asarray(tensor)))
        out = self._poll(lambda: self._store.collect.remote(seq))
        self._store.gc.remote(seq)
        return out

    def reduce(self, tensor: np.ndarray, dst_rank: int = 0, op: str = "sum"):
        reduced = self.allreduce(tensor, op)
        return reduced if self.rank == dst_rank else tensor

    def broadcast(self, tensor: np.ndarray, src_rank: int = 0) -> np.ndarray:
        seq = self._next_seq("bc")
        if self.rank == src_rank:
            ray_tpu.get(self._store.put.remote(seq, src_rank, np.asarray(tensor)))
        out = self._poll(lambda: self._store.collect.remote(seq, 1))
        self.barrier()
        if self.rank == src_rank:
            self._store.gc.remote(seq)
        return out[0]

    def reducescatter(self, tensor: np.ndarray, op: str = "sum") -> np.ndarray:
        reduced = self.allreduce(tensor, op)
        return np.array_split(reduced, self.world_size)[self.rank]

    def send(self, tensor: np.ndarray, dst_rank: int, tag: str = "") -> None:
        ray_tpu.get(
            self._store.put.remote(f"p2p:{self.rank}->{dst_rank}:{tag}",
                                   self.rank, np.asarray(tensor))
        )

    def recv(self, src_rank: int, tag: str = "") -> np.ndarray:
        seq = f"p2p:{src_rank}->{self.rank}:{tag}"
        out = self._poll(lambda: self._store.collect.remote(seq, 1))
        self._store.gc.remote(seq)
        return out[0]

    def barrier(self) -> None:
        # arrive() is idempotent per rank; poll until everyone has arrived.
        seq = self._next_seq("bar")
        deadline = time.monotonic() + 120.0
        while True:
            n = ray_tpu.get(self._store.arrive.remote(seq, self.rank))
            if n >= self.world_size:
                return
            if time.monotonic() > deadline:
                raise TimeoutError("barrier timed out")
            time.sleep(0.001)


class GroupManager:
    """Process-local registry of collective groups (reference ``collective.py:40``)."""

    def __init__(self):
        self._groups: Dict[str, CollectiveGroup] = {}
        self._lock = threading.Lock()

    def create_group(self, name: str, world_size: int, rank: int) -> CollectiveGroup:
        store_name = f"__ray_tpu_collective_store__{name}"
        store_cls = ray_tpu.remote(_RendezvousStore)
        if rank == 0:
            store = store_cls.options(name=store_name, lifetime="detached").remote(
                world_size
            )
        else:
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    store = ray_tpu.get_actor(store_name)
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.01)
        group = CollectiveGroup(name, world_size, rank, store)
        with self._lock:
            self._groups[name] = group
        return group

    def get_group(self, name: str) -> CollectiveGroup:
        try:
            return self._groups[name]
        except KeyError:
            raise ValueError(f"collective group {name!r} is not initialized") from None

    def destroy_group(self, name: str) -> None:
        with self._lock:
            group = self._groups.pop(name, None)
        if group is not None and group.rank == 0:
            try:
                store = ray_tpu.get_actor(f"__ray_tpu_collective_store__{name}")
                ray_tpu.kill(store)
            except Exception:
                pass


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    return _manager.create_group(group_name, world_size, rank)


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy_group(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get_group(group_name)
        return True
    except ValueError:
        return False


def get_rank(group_name: str = "default") -> int:
    return _manager.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get_group(group_name).world_size


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return _manager.get_group(group_name).allreduce(tensor, op)


def allgather(tensor, group_name: str = "default"):
    return _manager.get_group(group_name).allgather(tensor)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default", op: str = "sum"):
    return _manager.get_group(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _manager.get_group(group_name).broadcast(tensor, src_rank)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return _manager.get_group(group_name).reducescatter(tensor, op)


def send(tensor, dst_rank: int, group_name: str = "default", tag: str = ""):
    return _manager.get_group(group_name).send(tensor, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: str = ""):
    return _manager.get_group(group_name).recv(src_rank, tag)


def barrier(group_name: str = "default"):
    return _manager.get_group(group_name).barrier()
