"""Collective groups for ray_tpu (reference: ``python/ray/util/collective/collective.py``).

Two tiers, mirroring the reference's NCCL/Gloo split but TPU-native:

* **Device tier** (inside ``jit``/``shard_map``): collectives are XLA ops over
  ICI — use :mod:`ray_tpu.parallel` meshes and ``jax.lax.psum/all_gather/...``
  directly. Nothing to "initialize"; the mesh is the group.
* **Host tier** (this module): CPU/numpy collectives between ray_tpu actors,
  the Gloo-equivalent (reference ``gloo_collective_group.py``). Rendezvous is a
  named store actor (reference ``nccl_collective_group.py:29``); data moves
  through the object store. Used for coordinator-style reductions (metrics,
  rendezvous, weight broadcast between actor groups), not the training hot path.

Every op is built on a gc-safe gather in the store actor: buffers for a
sequence number are deleted only after every expected reader has consumed
them, so fast ranks can never garbage-collect a round out from under slow
ranks.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "product": lambda xs: np.prod(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
}


class _RendezvousStore:
    """Named actor used as the group rendezvous + data plane."""

    def __init__(self, world_size: int):
        self._world_size = world_size
        self._buffers: Dict[str, Dict[int, object]] = {}
        self._reads: Dict[str, set] = {}

    def put(self, seq: str, rank: int, value) -> None:
        self._buffers.setdefault(seq, {})[rank] = value

    def collect(self, seq: str, reader: int, num: Optional[int] = None,
                num_readers: Optional[int] = None):
        """Return all contributions once ``num`` arrived, else None.

        The entry is deleted only after ``num_readers`` distinct readers have
        received it.
        """
        want = num if num is not None else self._world_size
        bufs = self._buffers.get(seq, {})
        if len(bufs) < want:
            return None
        out = [bufs[r] for r in sorted(bufs)]
        reads = self._reads.setdefault(seq, set())
        reads.add(reader)
        if len(reads) >= (num_readers if num_readers is not None
                          else self._world_size):
            del self._buffers[seq]
            del self._reads[seq]
        return out

    def world_size(self) -> int:
        return self._world_size


class CollectiveGroup:
    """Per-process handle to one collective group (one per rank).

    All ranks must issue the same sequence of collective ops (the standard
    collective-programming contract); sequence numbers align rounds.
    """

    def __init__(self, name: str, world_size: int, rank: int, store):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._store = store
        self._seq = 0
        # Per-(peer, tag) p2p sequence counters: a second send with the same
        # tag before the first recv must land in a distinct buffer (no silent
        # overwrite). Sender and receiver count independently but stay in
        # lockstep because p2p is pairwise FIFO.
        self._p2p_send: Dict[tuple, int] = {}
        self._p2p_recv: Dict[tuple, int] = {}
        self._lock = threading.Lock()

    def _next_seq(self, op: str) -> str:
        with self._lock:
            self._seq += 1
            return f"{op}:{self._seq}"

    def _poll(self, fn, timeout_s: float = 120.0):
        deadline = time.monotonic() + timeout_s
        backoff = 0.0005
        while True:
            out = ray_tpu.get(fn())
            if out is not None:
                return out
            if time.monotonic() > deadline:
                raise TimeoutError(f"collective on group {self.name!r} timed out")
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.05)

    def _gather_round(self, value, contribute: bool = True) -> List:
        seq = self._next_seq("rnd")
        if contribute:
            ray_tpu.get(self._store.put.remote(seq, self.rank, value))
        return self._poll(
            lambda: self._store.collect.remote(seq, self.rank)
        )

    # -- ops ---------------------------------------------------------------
    def allgather(self, tensor: np.ndarray) -> List[np.ndarray]:
        return self._gather_round(np.asarray(tensor))

    def allreduce(self, tensor: np.ndarray, op: str = "sum") -> np.ndarray:
        parts = self.allgather(tensor)
        return _REDUCE_OPS[op](np.stack(parts))

    def reduce(self, tensor: np.ndarray, dst_rank: int = 0, op: str = "sum"):
        reduced = self.allreduce(tensor, op)
        return reduced if self.rank == dst_rank else tensor

    def broadcast(self, tensor: np.ndarray, src_rank: int = 0) -> np.ndarray:
        # Implemented as a gather of (rank == src contributions); every rank
        # participates in the round so sequence numbers stay aligned.
        parts = self.allgather(
            np.asarray(tensor) if self.rank == src_rank else np.zeros(0, np.int8)
        )
        # parts are ordered by rank; src's contribution is at src_rank.
        return parts[src_rank]

    def reducescatter(self, tensor: np.ndarray, op: str = "sum") -> np.ndarray:
        reduced = self.allreduce(tensor, op)
        return np.array_split(reduced, self.world_size)[self.rank]

    def barrier(self) -> None:
        self._gather_round(np.zeros(0, np.int8))

    def send(self, tensor: np.ndarray, dst_rank: int, tag: str = "") -> None:
        with self._lock:
            n = self._p2p_send.get((dst_rank, tag), 0) + 1
        seq = f"p2p:{self.rank}->{dst_rank}:{tag}:{n}"
        ray_tpu.get(self._store.put.remote(seq, self.rank, np.asarray(tensor)))
        # Count only after the put landed: a failed send can be retried
        # without desyncing the (peer, tag) stream.
        with self._lock:
            self._p2p_send[(dst_rank, tag)] = n

    def recv(self, src_rank: int, tag: str = "") -> np.ndarray:
        with self._lock:
            n = self._p2p_recv.get((src_rank, tag), 0) + 1
        seq = f"p2p:{src_rank}->{self.rank}:{tag}:{n}"
        out = self._poll(
            lambda: self._store.collect.remote(seq, self.rank, 1, 1)
        )
        # Count only after the message arrived: a timed-out recv can be
        # retried against the same sequence number.
        with self._lock:
            self._p2p_recv[(src_rank, tag)] = n
        return out[0]


class GroupManager:
    """Process-local registry of collective groups (reference ``collective.py:40``)."""

    def __init__(self):
        self._groups: Dict[str, CollectiveGroup] = {}
        self._lock = threading.Lock()

    def create_group(self, name: str, world_size: int, rank: int) -> CollectiveGroup:
        store_name = f"__ray_tpu_collective_store__{name}"
        store_cls = ray_tpu.remote(_RendezvousStore)
        if rank == 0:
            store = store_cls.options(name=store_name, lifetime="detached").remote(
                world_size
            )
        else:
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    store = ray_tpu.get_actor(store_name)
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.01)
        group = CollectiveGroup(name, world_size, rank, store)
        with self._lock:
            self._groups[name] = group
        return group

    def get_group(self, name: str) -> CollectiveGroup:
        try:
            return self._groups[name]
        except KeyError:
            raise ValueError(f"collective group {name!r} is not initialized") from None

    def destroy_group(self, name: str) -> None:
        with self._lock:
            group = self._groups.pop(name, None)
        if group is not None and group.rank == 0:
            try:
                store = ray_tpu.get_actor(f"__ray_tpu_collective_store__{name}")
                ray_tpu.kill(store)
            except Exception:
                pass


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    return _manager.create_group(group_name, world_size, rank)


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy_group(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get_group(group_name)
        return True
    except ValueError:
        return False


def get_rank(group_name: str = "default") -> int:
    return _manager.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get_group(group_name).world_size


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return _manager.get_group(group_name).allreduce(tensor, op)


def allgather(tensor, group_name: str = "default"):
    return _manager.get_group(group_name).allgather(tensor)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default", op: str = "sum"):
    return _manager.get_group(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _manager.get_group(group_name).broadcast(tensor, src_rank)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return _manager.get_group(group_name).reducescatter(tensor, op)


def send(tensor, dst_rank: int, group_name: str = "default", tag: str = ""):
    return _manager.get_group(group_name).send(tensor, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: str = ""):
    return _manager.get_group(group_name).recv(src_rank, tag)


def barrier(group_name: str = "default"):
    return _manager.get_group(group_name).barrier()
