"""State API: cluster introspection (reference: ``ray.util.state`` — api.py,
backed by dashboard StateHead + ``_private/state.py`` GlobalState).

Works against both runtimes: the in-process LocalRuntime answers from its own
tables; cluster mode queries the GCS.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import worker as _worker


def _core():
    return _worker.global_worker().core


def list_nodes() -> List[Dict[str, Any]]:
    return ray_tpu.nodes()


def list_actors(detail: bool = False) -> List[Dict[str, Any]]:
    core = _core()
    # Cluster runtime: ask the GCS.
    if hasattr(core, "gcs"):
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        reply = core.gcs.ListActors(pb.ListActorsRequest(all_namespaces=True))
        return [{
            "actor_id": a.actor_id.hex(),
            "class_name": a.class_name,
            "state": a.state,
            "name": a.name,
            "namespace": a.namespace,
            "node_id": a.node_id,
            "num_restarts": a.num_restarts,
            "death_cause": a.death_cause,
        } for a in reply.actors]
    # Local runtime.
    out = []
    for actor_id, meta in getattr(core, "_actor_meta", {}).items():
        out.append({
            "actor_id": actor_id.hex(),
            "class_name": meta.get("class_name", ""),
            "state": meta.get("state", ""),
            "name": meta.get("name", ""),
            "namespace": meta.get("namespace", ""),
            "node_id": core.node_id.hex(),
            "num_restarts": 0,
            "death_cause": "",
        })
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    core = _core()
    if hasattr(core, "gcs"):
        # The GCS keeps groups in-process; expose what the proto directory has.
        return getattr(core, "_pg_cache", [])
    return []


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    core = _core()
    store = getattr(core, "store", None) or getattr(core, "memory", None)
    out = []
    if store is not None:
        with store._lock:
            for oid, entry in list(store._objects.items())[:limit]:
                out.append({
                    "object_id": oid.hex(),
                    "ready": entry.ready.is_set(),
                    "task_id": oid.task_id().hex(),
                })
    return out


def list_tasks(limit: int = 1000,
               filters: Optional[Dict[str, Any]] = None,
               include_spans: bool = False) -> List[Dict[str, Any]]:
    """Recent task state transitions from the GCS task-event sink
    (reference C32: ``ray.util.state.list_tasks`` over the GCS task
    manager). Cluster mode only; local mode returns []. Tracing SPAN
    records ride the same sink; they are excluded unless
    ``include_spans`` (the timeline asks for them)."""
    core = _core()
    gcs = getattr(core, "gcs", None)
    if gcs is None:
        return []
    import pickle

    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    reply = gcs.KvGet(pb.KvRequest(ns="__task_events__", key="recent"))
    events = pickle.loads(reply.value) if reply.found else []
    if not include_spans:
        events = [e for e in events if e.get("state") != "SPAN"]
    if filters:
        events = [e for e in events
                  if all(e.get(k) == v for k, v in filters.items())]
    return events[-limit:]


def task_timeline() -> List[Dict[str, Any]]:
    """Chrome-trace events built from the cluster task-event sink,
    merged with tracing spans when RAY_TPU_TRACING is on (reference:
    ``ray timeline`` merging task events; spans add cross-process
    parent->child flow arrows)."""
    from ray_tpu.util.tracing import spans_to_chrome_events

    spans: Dict[str, Dict[str, Any]] = {}
    span_records: List[Dict[str, Any]] = []
    out: List[Dict[str, Any]] = []
    for e in list_tasks(limit=100000, include_spans=True):
        if e["state"] == "SPAN":
            span_records.append(e)
            continue
        tid = e["task_id"]
        if e["state"] == "RUNNING":
            spans[tid] = e
        elif e["state"] in ("FINISHED", "FAILED") and tid in spans:
            start = spans.pop(tid)
            out.append({
                "name": e["name"], "cat": "task",
                "ph": "X", "ts": start["ts"] * 1e6,
                "dur": max(e["ts"] - start["ts"], 0) * 1e6,
                "pid": e.get("node_id", ""), "tid": e.get("worker_id", ""),
                "args": {"state": e["state"], "task_id": tid,
                         **({"error": e["error"]} if "error" in e else {})},
            })
    out.extend(spans_to_chrome_events(span_records))
    return out


def list_cluster_events(limit: int = 1000) -> List[Dict[str, Any]]:
    """Structured lifecycle events from the GCS export-event buffer
    (reference C11: RayEvent export framework; `ray list cluster-events`).
    Cluster mode only."""
    core = _core()
    gcs = getattr(core, "gcs", None)
    if gcs is None:
        return []
    import pickle

    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    reply = gcs.KvGet(pb.KvRequest(ns="__events__", key=""))
    events = pickle.loads(reply.value) if reply.found else []
    return events[-limit:]


def list_flight_events(types: Optional[List[str]] = None,
                       subject: Optional[Dict[str, str]] = None,
                       since: Optional[float] = None,
                       until: Optional[float] = None,
                       limit: int = 1000) -> List[Dict[str, Any]]:
    """Causally-linked control-plane events from the cluster flight
    recorder (``ray-tpu why`` / the dashboard timeline feed on it).

    Cluster mode queries the GCS-journaled store through the reserved
    ``__events__`` KV namespace (a JSON dict key filters server-side;
    ``since``/``until`` under 1e9 are relative seconds before now);
    local mode reads this process's ring — the same records, since
    every plane of a local cluster emits into one process."""
    core = _core()
    gcs = getattr(core, "gcs", None)
    if gcs is None:
        from ray_tpu._private import events as _events

        return _events.local_events(types=types, subject=subject,
                                    since=since, until=until, limit=limit)
    import json
    import pickle

    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    query = json.dumps({"types": types, "subject": subject,
                        "since": since, "until": until, "limit": limit})
    reply = gcs.KvGet(pb.KvRequest(ns="__events__", key=query))
    if not reply.found:
        raise RuntimeError(
            f"flight-event query failed: {reply.value.decode()}")
    return pickle.loads(reply.value)


def memory_summary() -> Dict[str, Any]:
    """Cluster object-memory report (reference: ``ray memory`` — per-object
    size, store locations, and reference holders from the GCS tables)."""
    core = _core()
    gcs = getattr(core, "gcs", None)
    if gcs is None:
        store = getattr(core, "store", None) or getattr(core, "memory", None)
        n = store.size() if store is not None else 0
        return {"objects": [], "num_tracked": n, "total_bytes": 0,
                "num_freed_remembered": 0}
    import pickle

    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    reply = gcs.KvGet(pb.KvRequest(ns="__memory__", key=""))
    return pickle.loads(reply.value)


def summarize_cluster() -> Dict[str, Any]:
    return {
        "nodes": len([n for n in ray_tpu.nodes() if n.get("Alive", n.get("alive"))]),
        "total_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
        "actors": len(list_actors()),
        "timestamp": time.time(),
    }
