"""Compatibility shims over moving jax APIs.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` export; the framework supports both ends of
that migration (the pinned CI jax still ships only the experimental
path). Import it from here, never from ``jax`` directly.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _sm
    # Some versions expose ``jax.shard_map`` as a MODULE; the callable
    # lives one attribute deeper.
    _shard_map = _sm if callable(_sm) else _sm.shard_map
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``jax.shard_map`` across the experimental->top-level migration.

    The replication-check knob was renamed ``check_rep`` -> ``check_vma``
    mid-migration; translate whichever spelling the caller used into the
    one the installed jax accepts.
    """
    for theirs, ours in (("check_vma", "check_rep"),
                         ("check_rep", "check_vma")):
        if theirs in kwargs and theirs not in _PARAMS and ours in _PARAMS:
            kwargs[ours] = kwargs.pop(theirs)
    return _shard_map(*args, **kwargs)


__all__ = ["shard_map"]
