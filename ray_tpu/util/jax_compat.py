"""Compatibility shims over moving jax APIs.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` export; the framework supports both ends of
that migration (the pinned CI jax still ships only the experimental
path). Import it from here, never from ``jax`` directly.

This module also pins ``jax_threefry_partitionable``: with the legacy
non-partitionable threefry, the SPMD partitioner generates
DIFFERENT random values for the same key depending on the output
sharding (a ``jax.random.normal`` jitted with a sharded out_sharding
diverges from its unsharded twin), so model init was a function of the
mesh layout — cross-layout loss parity is impossible under that
regime. Partitionable threefry makes RNG output sharding-invariant.
"""

from __future__ import annotations

import inspect

import jax

try:
    jax.config.update("jax_threefry_partitionable", True)
except Exception:  # pragma: no cover - flag removed once it's the default
    pass

try:
    from jax import shard_map as _sm
    # Some versions expose ``jax.shard_map`` as a MODULE; the callable
    # lives one attribute deeper.
    _shard_map = _sm if callable(_sm) else _sm.shard_map
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``jax.shard_map`` across the experimental->top-level migration.

    The replication-check knob was renamed ``check_rep`` -> ``check_vma``
    mid-migration; translate whichever spelling the caller used into the
    one the installed jax accepts.
    """
    for theirs, ours in (("check_vma", "check_rep"),
                         ("check_rep", "check_vma")):
        if theirs in kwargs and theirs not in _PARAMS and ours in _PARAMS:
            kwargs[ours] = kwargs.pop(theirs)
    return _shard_map(*args, **kwargs)


__all__ = ["shard_map"]
