"""User-facing metrics: Counter / Gauge / Histogram + Prometheus text export.

Reference: ``ray.util.metrics`` over the C++ OpenCensus pipeline (SURVEY.md
C10 — ``stats/metric.h:103``, exported to the per-node agent then
Prometheus). This build keeps a process-local registry and renders the
Prometheus text format; the dashboard serves it at ``/metrics``.
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: List["Metric"] = []

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0)


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    @property
    def name(self) -> str:
        return self._name

    @property
    def description(self) -> str:
        return self._description

    @property
    def tag_keys(self) -> Tuple[str, ...]:
        return self._tag_keys

    def samples(self) -> List[Tuple[str, Tuple, float]]:
        """Current (name, label_tuple, value) samples — the push-plane
        snapshot the metrics pusher ships to the head TSDB."""
        return []

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _render_labels(self, key: Tuple) -> str:
        if not key:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in key)
        return "{" + inner + "}"


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] += value

    def samples(self):
        with self._lock:
            return [(self._name, key, v) for key, v in self._values.items()]

    def render(self) -> List[str]:
        out = [f"# HELP {self._name} {self._description}",
               f"# TYPE {self._name} counter"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self._name}{self._render_labels(key)} {v}")
        return out


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] = value

    def samples(self):
        with self._lock:
            return [(self._name, key, v) for key, v in self._values.items()]

    def render(self) -> List[str]:
        out = [f"# HELP {self._name} {self._description}",
               f"# TYPE {self._name} gauge"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self._name}{self._render_labels(key)} {v}")
        return out


class Histogram(Metric):
    def __init__(self, name, description="", boundaries=DEFAULT_BUCKETS,
                 tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._bounds = tuple(boundaries)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = defaultdict(float)
        self._totals: Dict[Tuple, int] = defaultdict(int)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self._bounds) + 1))
            counts[bisect.bisect_left(self._bounds, value)] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def render(self) -> List[str]:
        out = [f"# HELP {self._name} {self._description}",
               f"# TYPE {self._name} histogram"]
        with self._lock:
            for key, counts in self._counts.items():
                cum = 0
                for bound, c in zip(self._bounds, counts):
                    cum += c
                    labels = dict(key)
                    labels["le"] = str(bound)
                    inner = ",".join(f'{k}="{v}"'
                                     for k, v in sorted(labels.items()))
                    out.append(f"{self._name}_bucket{{{inner}}} {cum}")
                labels = dict(key)
                labels["le"] = "+Inf"
                inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
                out.append(f"{self._name}_bucket{{{inner}}} {self._totals[key]}")
                out.append(
                    f"{self._name}_sum{self._render_labels(key)} {self._sums[key]}")
                out.append(
                    f"{self._name}_count{self._render_labels(key)} {self._totals[key]}")
        return out


    def samples(self):
        # Histograms ship their sum and count (rate + mean latency are
        # derivable at query time; per-bucket series would multiply the
        # TSDB's series count by the bucket count).
        with self._lock:
            out = []
            for key, total in self._totals.items():
                out.append((f"{self._name}_count", key, float(total)))
                out.append((f"{self._name}_sum", key, self._sums[key]))
            return out

    def bucket_snapshot(self, tags: Optional[Dict[str, str]] = None
                        ) -> Tuple[Tuple[float, ...], List[int], int]:
        """``(bounds, per-bucket counts, total)`` merged across every
        label set matching ``tags`` (a subset filter; ``None`` = all).
        In-process consumers (the chip-pool SLO guard) diff successive
        snapshots to score a bounded window instead of the lifetime
        distribution."""
        want = tuple(sorted((tags or {}).items()))
        merged = [0] * (len(self._bounds) + 1)
        total = 0
        with self._lock:
            for key, counts in self._counts.items():
                kd = dict(key)
                if any(kd.get(k) != v for k, v in want):
                    continue
                for i, c in enumerate(counts):
                    merged[i] += c
                total += self._totals[key]
        return self._bounds, merged, total

    @staticmethod
    def percentile_from(bounds: Sequence[float], counts: Sequence[int],
                        q: float) -> Optional[float]:
        """Upper-bound percentile estimate from bucket counts (the last
        finite bound stands in for the +Inf bucket). ``None`` when the
        window holds no observations."""
        total = sum(counts)
        if total <= 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                return (bounds[i] if i < len(bounds)
                        else bounds[-1] if bounds else float("inf"))
        return bounds[-1] if bounds else float("inf")


def prometheus_text() -> str:
    """Render every registered metric (the /metrics endpoint body)."""
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        lines.extend(m.render())
    return "\n".join(lines) + "\n"


def all_metrics() -> List[Metric]:
    with _registry_lock:
        return list(_registry)


def collect_samples() -> List[Tuple[str, Tuple, float]]:
    """Snapshot every registered metric's samples (push-plane payload)."""
    out: List[Tuple[str, Tuple, float]] = []
    for m in all_metrics():
        out.extend(m.samples())
    return out
