"""Placement groups: gang reservation of resource bundles across the cluster.

Re-design of the reference API (reference:
``python/ray/util/placement_group.py:145`` + the GCS 2PC scheduler,
``gcs_placement_group_scheduler.cc`` / ``bundle_scheduling_policy.h``): the
GCS reserves every bundle via prepare/commit on the node managers, retrying
until feasible; tasks and actors then target a bundle with
``PlacementGroupSchedulingStrategy`` (or the ``placement_group=`` option)
and consume the reserved resources instead of free capacity.

TPU-native strategy semantics: ``PACK`` prefers a single node and, failing
that, nodes sharing one ``tpu-slice`` label — i.e. one ICI-connected slice —
so collectives inside the group ride ICI, not DCN (SURVEY.md §7 step 4).
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.protobuf import ray_tpu_pb2 as pb

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a (possibly still-placing) placement group."""

    def __init__(self, group_id: bytes, bundles: List[Dict[str, float]],
                 strategy: str = "PACK", name: str = ""):
        self.id = group_id
        self.bundle_specs = list(bundles)
        self.strategy = strategy
        self.name = name

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def _state(self) -> pb.PlacementGroupInfo:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod.global_worker().core
        info = core.get_placement_group(self.id)
        if info is None:
            raise ValueError(
                f"placement group {self.id.hex()[:12]} does not exist")
        return info

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block until every bundle is reserved (state CREATED).

        Returns False on timeout or infeasibility (reference: ``pg.wait``).
        """
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            state = self._state().state
            if state == "CREATED":
                return True
            if state in ("INFEASIBLE", "REMOVED"):
                return False
            time.sleep(0.05)
        return False

    def ready(self):
        """ObjectRef that resolves once the group is usable — implemented, as
        in the reference, by scheduling a trivial task into bundle 0 so the
        full lease path is exercised (``placement_group.py:145`` ready())."""
        import ray_tpu

        @ray_tpu.remote(num_cpus=0)
        def _pg_ready():
            return True

        return _pg_ready.options(
            placement_group=self, placement_group_bundle_index=0).remote()

    def bundle_node_ids(self) -> List[str]:
        """Node id hosting each bundle (empty strings until placed)."""
        return [b.node_id for b in self._state().bundles]

    def __repr__(self):
        return (f"PlacementGroup(id={self.id.hex()[:12]}, "
                f"bundles={self.bundle_specs}, strategy={self.strategy!r})")


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    """Reserve a group of resource bundles (reference:
    ``python/ray/util/placement_group.py:145``).

    Placement is asynchronous: use ``pg.wait()`` / ``pg.ready()`` to block.
    """
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"Invalid strategy {strategy!r}; expected one of "
            f"{VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement_group requires at least one bundle")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"each bundle must be a non-empty dict, got {b!r}")
        if any(v < 0 for v in b.values()):
            raise ValueError(f"bundle resources must be >= 0: {b!r}")
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker().core
    group_id = uuid.uuid4().bytes
    req = pb.CreatePlacementGroupRequest(
        group_id=group_id, name=name, strategy=strategy)
    for i, b in enumerate(bundles):
        bundle = pb.Bundle(index=i)
        for k, v in b.items():
            bundle.resources[k] = float(v)
        req.bundles.append(bundle)
    core.create_placement_group(req)
    return PlacementGroup(group_id, bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release every bundle reservation (reference: remove_placement_group)."""
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker().core
    core.remove_placement_group(pg.id)


def placement_group_table(pg: Optional[PlacementGroup] = None) -> Dict:
    """Debug view of one (or every) placement group."""
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker().core
    if pg is not None:
        info = core.get_placement_group(pg.id)
        if info is None:
            raise ValueError(
                f"placement group {pg.id.hex()[:12]} does not exist")
        return _info_to_dict(info)
    raise NotImplementedError("pass a PlacementGroup handle")


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The placement group capturing the current task (if any) — set when a
    task scheduled with ``placement_group_capture_child_tasks=True`` runs."""
    from ray_tpu._private import pg_context

    ctx = pg_context.get()
    if ctx is None:
        return None
    group_id, _bundle, _capture = ctx
    return PlacementGroup(group_id, [], "PACK")


def _info_to_dict(info: pb.PlacementGroupInfo) -> Dict:
    return {
        "placement_group_id": bytes(info.group_id).hex(),
        "name": info.name,
        "strategy": info.strategy,
        "state": info.state,
        "bundles": {b.index: dict(b.resources) for b in info.bundles},
        "bundles_to_node_id": {b.index: b.node_id for b in info.bundles},
    }
