"""Public distributed utilities (reference: ``python/ray/util/__init__.py``)."""

from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    get_current_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    DoesNotExist,
    Exists,
    In,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    NotIn,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "placement_group_table",
    "get_current_placement_group",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "In",
    "NotIn",
    "Exists",
    "DoesNotExist",
]
