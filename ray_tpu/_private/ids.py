"""Unique identifiers for jobs, tasks, actors, objects, nodes, and placement groups.

TPU-native re-design of the reference id model (reference: ``src/ray/common/id.h``,
``src/ray/common/id_def.h``, spec ``src/ray/design_docs/id_specification.md``):
ids are fixed-size byte strings; an ``ObjectID`` embeds the ``TaskID`` that created
it plus a return/put index, which gives every object a lineage pointer for free
(used by lineage reconstruction). A ``TaskID`` embeds the ``ActorID`` (or a nil
actor id for normal tasks), and an ``ActorID`` embeds the ``JobID``.

Sizes (bytes):
    JobID            4
    ActorID         12  = 8 unique + 4 job
    TaskID          24  = 12 unique + 12 actor
    ObjectID        28  = 24 task + 4 index (little-endian uint32)
    NodeID          16
    WorkerID        16
    PlacementGroupID 16
    ClusterID       16
"""

from __future__ import annotations

import os
import threading
from typing import ClassVar


class _FastRandom:
    """Buffered unique-id entropy: one ``os.urandom`` syscall refills 8KB
    instead of one syscall per id — id creation is on the task-submit hot
    path (reference: ids only need uniqueness, not crypto strength, and
    the reference's ``FromRandom`` likewise uses a userspace PRNG)."""

    def __init__(self):
        self._buf = b""
        self._off = 0
        self._lock = threading.Lock()

    def take(self, n: int) -> bytes:
        with self._lock:
            if self._off + n > len(self._buf):
                self._buf = os.urandom(8192)
                self._off = 0
            out = self._buf[self._off:self._off + n]
            self._off += n
            return out


_rng = _FastRandom()
# A fork must not replay the parent's entropy buffer (duplicate ids).
if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=lambda: _rng.__init__())


def _random_bytes(n: int) -> bytes:
    return _rng.take(n)


class BaseID:
    """Immutable fixed-size binary id with hex repr."""

    SIZE: ClassVar[int] = 16
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {len(binary) if isinstance(binary, bytes) else type(binary)}"
            )
        object.__setattr__(self, "_binary", binary)
        object.__setattr__(self, "_hash", hash((type(self).__name__, binary)))

    def __setattr__(self, *a):  # immutability
        raise AttributeError(f"{type(self).__name__} is immutable")

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def from_binary(cls, binary: bytes):
        return cls(binary)

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    # -- accessors --------------------------------------------------------
    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def is_nil(self) -> bool:
        return self._binary == b"\xff" * self.SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self).from_binary, (self._binary,))


class ClusterID(BaseID):
    SIZE = 16


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class JobID(BaseID):
    SIZE = 4
    _counter_lock: ClassVar[threading.Lock] = threading.Lock()
    _counter: ClassVar[int] = 0

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(cls.SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._binary, "little")


class ActorID(BaseID):
    SIZE = 12
    UNIQUE_BYTES = 8

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_random_bytes(cls.UNIQUE_BYTES) + job_id.binary())

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        return cls(b"\xff" * cls.UNIQUE_BYTES + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[self.UNIQUE_BYTES :])


class TaskID(BaseID):
    SIZE = 24
    UNIQUE_BYTES = 12

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(_random_bytes(cls.UNIQUE_BYTES) + ActorID.nil_for_job(job_id).binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_random_bytes(cls.UNIQUE_BYTES) + actor_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        # Deterministic: the creation task of an actor is identified by the actor id.
        return cls(b"\x00" * cls.UNIQUE_BYTES + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(b"\x01" * cls.UNIQUE_BYTES + ActorID.nil_for_job(job_id).binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._binary[self.UNIQUE_BYTES :])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    SIZE = 28
    INDEX_BYTES = 4
    MAX_INDEX = 2**32 - 1

    @classmethod
    def from_task(cls, task_id: TaskID, index: int) -> "ObjectID":
        if not (0 <= index <= cls.MAX_INDEX):
            raise ValueError(f"object index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(cls.INDEX_BYTES, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[: TaskID.SIZE])

    def index(self) -> int:
        return int.from_bytes(self._binary[TaskID.SIZE :], "little")

    def job_id(self) -> JobID:
        return self.task_id().job_id()
