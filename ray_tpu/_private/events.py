"""Task-event recording + chrome-trace timeline export.

Reference: the profile-event path (SURVEY.md §5 tracing) — per-task events
buffered in the CoreWorker (``task_event_buffer.h:224``) and dumped with
``ray timeline`` / ``GlobalState.chrome_tracing_dump`` (_private/state.py:442).
Events here are recorded per process (driver submission spans + local-mode
execution spans) and rendered in the chrome ``about://tracing`` JSON format.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_enabled = True
MAX_EVENTS = 200_000

# Overflow accounting: both event buffers here shed load silently by
# design (events are best-effort), but SILENT shedding is an
# observability hole — an overloaded span pipeline looks identical to a
# quiet one. Every drop increments ray_tpu_events_dropped_total (tagged
# by buffer) and the FIRST drop per buffer logs once per process.
_drop_logged: set = set()


def _count_dropped(buffer: str, n: int) -> None:
    if n <= 0:
        return
    try:
        # Lazy import: events.py is imported early in process bootstrap,
        # before the metrics registry is guaranteed importable.
        from ray_tpu._private import metrics_defs as mdefs

        mdefs.EVENTS_DROPPED.inc(n, tags={"buffer": buffer})
    except Exception:  # noqa: BLE001 — accounting must never break adds
        pass
    if buffer not in _drop_logged:
        _drop_logged.add(buffer)
        logger.warning(
            "event buffer %r overflowed: dropped %d record(s) — further "
            "drops are counted in ray_tpu_events_dropped_total but not "
            "logged", buffer, n)


def dropped_counts() -> Dict[str, float]:
    """Per-buffer drop totals recorded so far by this process."""
    try:
        from ray_tpu._private import metrics_defs as mdefs

        return {dict(key).get("buffer", "?"): v
                for _, key, v in mdefs.EVENTS_DROPPED.samples()}
    except Exception:  # noqa: BLE001
        return {}


class BufferedPublisher:
    """Lock-guarded buffer + daemon flush thread that Publishes pickled
    batches to one GCS pubsub channel. Shared by the worker task-event
    reporter and the tracing span reporter (one flush pattern to keep
    correct, not two)."""

    def __init__(self, channel: str, gcs_getter, period_s: float = 0.2,
                 cap: int = 4000):
        self._channel = channel
        # Returns the GCS stub or None. A getter that auto-initializes a
        # runtime would resurrect a global worker from this daemon thread
        # after shutdown — callers must pass a non-initializing one.
        self._gcs_getter = gcs_getter
        self._period = period_s
        self._cap = cap
        self._buf: List[Any] = []
        self._buf_lock = threading.Lock()
        threading.Thread(target=self._flush_loop, daemon=True,
                         name=f"pub-{channel}").start()

    def add(self, record: Any) -> None:
        shed = 0
        with self._buf_lock:
            self._buf.append(record)
            if len(self._buf) > self._cap:
                shed = self._cap // 2
                del self._buf[:shed]
        if shed:
            _count_dropped(f"publisher:{self._channel}", shed)

    def _flush_loop(self) -> None:
        import pickle

        while True:
            time.sleep(self._period)
            with self._buf_lock:
                buf, self._buf = self._buf, []
            if not buf:
                continue
            try:
                gcs = self._gcs_getter()
                if gcs is None:
                    continue  # no runtime (e.g. after shutdown): drop
                from ray_tpu.protobuf import ray_tpu_pb2 as pb

                gcs.Publish(pb.PublishRequest(
                    channel=self._channel, data=pickle.dumps(buf)))
            except Exception:  # noqa: BLE001 — events are best-effort
                pass


def record(name: str, category: str, start_s: float, end_s: float,
           tid: Optional[int] = None, **extra) -> None:
    if not _enabled:
        return
    ev = {
        "name": name,
        "cat": category,
        "ph": "X",
        "ts": start_s * 1e6,
        "dur": (end_s - start_s) * 1e6,
        "pid": 0,
        "tid": tid if tid is not None else threading.get_ident() % 100000,
    }
    if extra:
        ev["args"] = extra
    dropped = False
    with _lock:
        if len(_events) < MAX_EVENTS:
            _events.append(ev)
        else:
            dropped = True
    if dropped:
        _count_dropped("timeline", 1)


class span:
    """Context manager recording one event."""

    def __init__(self, name: str, category: str = "task", **extra):
        self.name = name
        self.category = category
        self.extra = extra

    def __enter__(self):
        self.start = time.time()
        return self

    def __exit__(self, *exc):
        record(self.name, self.category, self.start, time.time(),
               **self.extra)
        return False


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Dump recorded events (chrome trace format). Reference: ``ray timeline``."""
    with _lock:
        events = list(_events)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def clear() -> None:
    with _lock:
        _events.clear()
