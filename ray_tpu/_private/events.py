"""Task-event recording + chrome-trace timeline export.

Reference: the profile-event path (SURVEY.md §5 tracing) — per-task events
buffered in the CoreWorker (``task_event_buffer.h:224``) and dumped with
``ray timeline`` / ``GlobalState.chrome_tracing_dump`` (_private/state.py:442).
Events here are recorded per process (driver submission spans + local-mode
execution spans) and rendered in the chrome ``about://tracing`` JSON format.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, Iterable, List, Optional, Set

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_enabled = True
MAX_EVENTS = 200_000

# Overflow accounting: both event buffers here shed load silently by
# design (events are best-effort), but SILENT shedding is an
# observability hole — an overloaded span pipeline looks identical to a
# quiet one. Every drop increments ray_tpu_events_dropped_total (tagged
# by buffer) and the FIRST drop per buffer logs once per process.
_drop_logged: set = set()


def _count_dropped(buffer: str, n: int) -> None:
    if n <= 0:
        return
    try:
        # Lazy import: events.py is imported early in process bootstrap,
        # before the metrics registry is guaranteed importable.
        from ray_tpu._private import metrics_defs as mdefs

        mdefs.EVENTS_DROPPED.inc(n, tags={"buffer": buffer})
    except Exception:  # noqa: BLE001 — accounting must never break adds
        pass
    if buffer not in _drop_logged:
        _drop_logged.add(buffer)
        logger.warning(
            "event buffer %r overflowed: dropped %d record(s) — further "
            "drops are counted in ray_tpu_events_dropped_total but not "
            "logged", buffer, n)


def dropped_counts() -> Dict[str, float]:
    """Per-buffer drop totals recorded so far by this process."""
    try:
        from ray_tpu._private import metrics_defs as mdefs

        return {dict(key).get("buffer", "?"): v
                for _, key, v in mdefs.EVENTS_DROPPED.samples()}
    except Exception:  # noqa: BLE001
        return {}


class BufferedPublisher:
    """Lock-guarded buffer + daemon flush thread that Publishes pickled
    batches to one GCS pubsub channel. Shared by the worker task-event
    reporter and the tracing span reporter (one flush pattern to keep
    correct, not two)."""

    def __init__(self, channel: str, gcs_getter, period_s: float = 0.2,
                 cap: int = 4000):
        self._channel = channel
        # Returns the GCS stub or None. A getter that auto-initializes a
        # runtime would resurrect a global worker from this daemon thread
        # after shutdown — callers must pass a non-initializing one.
        self._gcs_getter = gcs_getter
        self._period = period_s
        self._cap = cap
        self._buf: List[Any] = []
        self._buf_lock = threading.Lock()
        threading.Thread(target=self._flush_loop, daemon=True,
                         name=f"pub-{channel}").start()

    def add(self, record: Any) -> None:
        shed = 0
        with self._buf_lock:
            self._buf.append(record)
            if len(self._buf) > self._cap:
                shed = self._cap // 2
                del self._buf[:shed]
        if shed:
            _count_dropped(f"publisher:{self._channel}", shed)

    def _flush_loop(self) -> None:
        import pickle

        while True:
            time.sleep(self._period)
            with self._buf_lock:
                buf, self._buf = self._buf, []
            if not buf:
                continue
            try:
                gcs = self._gcs_getter()
                if gcs is None:
                    continue  # no runtime (e.g. after shutdown): drop
                from ray_tpu.protobuf import ray_tpu_pb2 as pb

                gcs.Publish(pb.PublishRequest(
                    channel=self._channel, data=pickle.dumps(buf)))
            except Exception:  # noqa: BLE001 — events are best-effort
                pass


def record(name: str, category: str, start_s: float, end_s: float,
           tid: Optional[int] = None, **extra) -> None:
    if not _enabled:
        return
    ev = {
        "name": name,
        "cat": category,
        "ph": "X",
        "ts": start_s * 1e6,
        "dur": (end_s - start_s) * 1e6,
        "pid": 0,
        "tid": tid if tid is not None else threading.get_ident() % 100000,
    }
    if extra:
        ev["args"] = extra
    dropped = False
    with _lock:
        if len(_events) < MAX_EVENTS:
            _events.append(ev)
        else:
            dropped = True
    if dropped:
        _count_dropped("timeline", 1)


class span:
    """Context manager recording one event."""

    def __init__(self, name: str, category: str = "task", **extra):
        self.name = name
        self.category = category
        self.extra = extra

    def __enter__(self):
        self.start = time.time()
        return self

    def __exit__(self, *exc):
        record(self.name, self.category, self.start, time.time(),
               **self.extra)
        return False


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Dump recorded events (chrome trace format). Reference: ``ray timeline``."""
    with _lock:
        events = list(_events)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def clear() -> None:
    with _lock:
        _events.clear()


# ---------------------------------------------------------------------------
# Cluster flight recorder: typed, causally-linked control-plane events.
#
# Every control-plane decision (lease transition, SLO reversal, drain,
# preemption notice, elastic recovery, probe-before-reap verdict, chaos
# injection) emits one record carrying an ``event_id``, a ``cause`` link
# to the parent event id, and ``subject`` keys (lease_id, replica, run,
# node, deployment, request_id...). Records land in a bounded per-process
# ring (queryable in local mode) and ride the same BufferedPublisher
# drop-accounting path as tracing spans to a bounded GCS store, so the
# fleet-operator question "why did my chips move" resolves to one
# connected chain instead of disconnected counters.
# ---------------------------------------------------------------------------

FLIGHT_CHANNEL = "FLIGHT_EVENT"
FLIGHT_RING_MAX = int(os.environ.get("RAY_TPU_FLIGHT_RING_MAX", "20000"))

_flight_lock = threading.Lock()
_flight: List[Dict[str, Any]] = []
_flight_publisher: Optional[BufferedPublisher] = None
_flight_pub_lock = threading.Lock()
# The GCS server process writes its own emissions straight into its
# store (it IS the sink — publishing to itself would deadlock the
# servicer thread on its own channel).
_local_sink: Optional[Callable[[List[Dict[str, Any]]], None]] = None


def set_local_sink(fn: Optional[Callable[[List[Dict[str, Any]]], None]]) -> None:
    """Route this process's flight events directly to ``fn(batch)``
    instead of the pubsub publisher (used by the GCS server process)."""
    global _local_sink
    _local_sink = fn


def _get_flight_publisher() -> BufferedPublisher:
    global _flight_publisher
    with _flight_pub_lock:
        if _flight_publisher is None:
            def gcs_getter():
                # Non-initializing: a flush thread must never resurrect
                # a global worker after shutdown (tracing._live_core).
                from ray_tpu._private import worker as worker_mod

                w = getattr(worker_mod, "_global_worker", None)
                core = None if w is None else w.core
                return getattr(core, "gcs", None) if core else None

            _flight_publisher = BufferedPublisher(FLIGHT_CHANNEL, gcs_getter)
        return _flight_publisher


def _flight_process_ids() -> Dict[str, str]:
    try:
        from ray_tpu.util.tracing import _process_ids

        return _process_ids()
    except Exception:  # noqa: BLE001
        return {"worker_id": "driver", "node_id": ""}


def emit(etype: str, cause: Optional[str] = None,
         subject: Optional[Dict[str, Any]] = None, **attrs) -> str:
    """Record one flight event; returns its event id.

    ``cause`` is the parent event id ("" breaks the chain); ``subject``
    keys identify what the event is about (lease_id, replica, run, node,
    deployment, request_id, trace_id). Extra keyword attrs ride under
    ``attrs``. Never raises: the recorder is best-effort by design."""
    event_id = uuid.uuid4().hex[:16]
    try:
        rec: Dict[str, Any] = {
            "event_id": event_id,
            "type": str(etype),
            "ts": time.time(),
            "cause": str(cause or ""),
            "subject": {str(k): str(v) for k, v in (subject or {}).items()
                        if v not in (None, "")},
            **_flight_process_ids(),
        }
        if attrs:
            rec["attrs"] = {str(k): v for k, v in attrs.items()}
        evicted = 0
        with _flight_lock:
            _flight.append(rec)
            if len(_flight) > FLIGHT_RING_MAX:
                evicted = len(_flight) - FLIGHT_RING_MAX
                del _flight[:evicted]
        if evicted:
            _count_dropped("flight", evicted)
        try:
            from ray_tpu._private import metrics_defs as mdefs

            mdefs.EVENTS_TOTAL.inc(tags={"type": str(etype)})
        except Exception:  # noqa: BLE001
            pass
        sink = _local_sink
        if sink is not None:
            sink([rec])
        else:
            _get_flight_publisher().add(rec)
    except Exception:  # noqa: BLE001 — recording must never break callers
        logger.debug("flight emit failed", exc_info=True)
    return event_id


def _subject_matches(rec: Dict[str, Any], subject: Dict[str, Any]) -> bool:
    sub = rec.get("subject", {})
    return all(sub.get(str(k)) == str(v) for k, v in subject.items())


def match_events(records: Iterable[Dict[str, Any]],
                 types: Optional[Iterable[str]] = None,
                 subject: Optional[Dict[str, Any]] = None,
                 since: Optional[float] = None,
                 until: Optional[float] = None,
                 limit: int = 1000) -> List[Dict[str, Any]]:
    """Filter flight records by type set / subject keys / time window.
    Shared by the local ring, the GCS query path, and the CLI so every
    surface answers filters identically."""
    tset = {str(t) for t in types} if types else None
    out = []
    for r in records:
        if tset is not None and r.get("type") not in tset:
            continue
        if subject and not _subject_matches(r, subject):
            continue
        ts = r.get("ts", 0.0)
        if since is not None and ts < since:
            continue
        if until is not None and ts > until:
            continue
        out.append(r)
    return out[-max(int(limit), 0):]


def local_events(types: Optional[Iterable[str]] = None,
                 subject: Optional[Dict[str, Any]] = None,
                 since: Optional[float] = None,
                 until: Optional[float] = None,
                 limit: int = 1000) -> List[Dict[str, Any]]:
    """Query this process's flight ring (the source of truth in local
    mode, where every plane shares one process). ``since``/``until``
    under 1e9 are relative seconds before now — the same convention the
    GCS ``__events__`` query path answers, so callers can switch
    transports without changing their window arguments."""
    now = time.time()
    if since is not None and float(since) < 1e9:
        since = now - float(since)
    if until is not None and float(until) < 1e9:
        until = now - float(until)
    with _flight_lock:
        recs = list(_flight)
    return match_events(recs, types=types, subject=subject,
                        since=since, until=until, limit=limit)


def latest_event_id(types: Iterable[str],
                    subject: Optional[Dict[str, Any]] = None) -> str:
    """Newest in-ring event id matching ``types`` (+ subject keys), or
    "". Best-effort cause inference for sites that observe an effect
    (a dead replica, a drain rejection) without the trigger's id in
    hand — correct in-process, empty across process boundaries."""
    tset = {str(t) for t in types}
    with _flight_lock:
        for rec in reversed(_flight):
            if rec.get("type") in tset and (
                    not subject or _subject_matches(rec, subject)):
                return rec.get("event_id", "")
    return ""


def causal_chain(records: List[Dict[str, Any]],
                 seed_ids: Iterable[str],
                 subject_rounds: int = 1) -> List[Dict[str, Any]]:
    """Causal closure of the seed events over ``records``: ancestors via
    ``cause`` links, descendants via reverse links, plus
    ``subject_rounds`` rounds of subject-join (events sharing any
    subject key=value with the selected set, re-closed causally each
    round — this is how a request's chain picks up the lease reversal
    that shares only a lease_id with the drain's cause). Sorted by ts."""
    by_id = {r["event_id"]: r for r in records if r.get("event_id")}
    children: Dict[str, List[str]] = {}
    for r in records:
        c = r.get("cause", "")
        if c:
            children.setdefault(c, []).append(r.get("event_id", ""))

    def close(selected: Set[str]) -> Set[str]:
        frontier = list(selected)
        while frontier:
            eid = frontier.pop()
            rec = by_id.get(eid)
            if rec is None:
                continue
            cause = rec.get("cause", "")
            if cause and cause in by_id and cause not in selected:
                selected.add(cause)
                frontier.append(cause)
            for kid in children.get(eid, ()):
                if kid and kid not in selected:
                    selected.add(kid)
                    frontier.append(kid)
        return selected

    selected = close({e for e in seed_ids if e in by_id})
    for _ in range(max(subject_rounds, 0)):
        pairs = set()
        for eid in selected:
            for k, v in by_id[eid].get("subject", {}).items():
                pairs.add((k, v))
        added = {r["event_id"] for r in records
                 if r.get("event_id") and r["event_id"] not in selected
                 and any((k, v) in pairs
                         for k, v in r.get("subject", {}).items())}
        if not added:
            break
        selected = close(selected | added)
    return sorted((by_id[e] for e in selected), key=lambda r: r.get("ts", 0.0))


def flight_span_records(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Map flight events onto the tracing span-record shape so
    ``spans_to_chrome_events`` renders cause links as chrome flow
    arrows alongside real spans in ``ray-tpu timeline``."""
    out = []
    for r in records:
        sub = r.get("subject", {})
        out.append({
            "name": r.get("type", "event"), "kind": "flight",
            "trace_id": sub.get("request_id") or sub.get("trace_id")
            or sub.get("lease_id") or "flight",
            "span_id": r.get("event_id", ""),
            "parent_span_id": r.get("cause", ""),
            "ts": r.get("ts", 0.0), "dur": 0.0,
            "node_id": r.get("node_id", ""),
            "worker_id": r.get("worker_id", "control"),
        })
    return out


def clear_flight() -> None:
    with _flight_lock:
        _flight.clear()
