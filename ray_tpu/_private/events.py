"""Task-event recording + chrome-trace timeline export.

Reference: the profile-event path (SURVEY.md §5 tracing) — per-task events
buffered in the CoreWorker (``task_event_buffer.h:224``) and dumped with
``ray timeline`` / ``GlobalState.chrome_tracing_dump`` (_private/state.py:442).
Events here are recorded per process (driver submission spans + local-mode
execution spans) and rendered in the chrome ``about://tracing`` JSON format.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_enabled = True
MAX_EVENTS = 200_000


def record(name: str, category: str, start_s: float, end_s: float,
           tid: Optional[int] = None, **extra) -> None:
    if not _enabled:
        return
    ev = {
        "name": name,
        "cat": category,
        "ph": "X",
        "ts": start_s * 1e6,
        "dur": (end_s - start_s) * 1e6,
        "pid": 0,
        "tid": tid if tid is not None else threading.get_ident() % 100000,
    }
    if extra:
        ev["args"] = extra
    with _lock:
        if len(_events) < MAX_EVENTS:
            _events.append(ev)


class span:
    """Context manager recording one event."""

    def __init__(self, name: str, category: str = "task", **extra):
        self.name = name
        self.category = category
        self.extra = extra

    def __enter__(self):
        self.start = time.time()
        return self

    def __exit__(self, *exc):
        record(self.name, self.category, self.start, time.time(),
               **self.extra)
        return False


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Dump recorded events (chrome trace format). Reference: ``ray timeline``."""
    with _lock:
        events = list(_events)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def clear() -> None:
    with _lock:
        _events.clear()
