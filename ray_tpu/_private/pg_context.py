"""Per-task placement-group capture context.

Reference: ``placement_group_capture_child_tasks`` semantics — a task running
inside a capturing placement group schedules its children into the same
group by default. The executing worker sets this context around user code;
submit paths read it when no explicit placement option is given.
"""

from __future__ import annotations

import contextvars
from typing import Optional, Tuple

# ContextVar, not threading.local: plain worker threads each get their own
# context (same semantics as before), and on an async actor's event loop
# every asyncio task carries its own copy, so interleaved coroutines don't
# race on set/clear.
_ctx: contextvars.ContextVar[Optional[Tuple[bytes, int, bool]]] = \
    contextvars.ContextVar("ray_tpu_pg_context", default=None)


def set(group_id: bytes, bundle_index: int, capture: bool) -> None:  # noqa: A001
    _ctx.set((group_id, bundle_index, capture))


def clear() -> None:
    _ctx.set(None)


def get() -> Optional[Tuple[bytes, int, bool]]:
    return _ctx.get()
