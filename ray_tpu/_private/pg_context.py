"""Per-task placement-group capture context.

Reference: ``placement_group_capture_child_tasks`` semantics — a task running
inside a capturing placement group schedules its children into the same
group by default. The executing worker sets this context around user code;
submit paths read it when no explicit placement option is given.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

_local = threading.local()


def set(group_id: bytes, bundle_index: int, capture: bool) -> None:  # noqa: A001
    _local.ctx = (group_id, bundle_index, capture)


def clear() -> None:
    _local.ctx = None


def get() -> Optional[Tuple[bytes, int, bool]]:
    return getattr(_local, "ctx", None)
