"""The per-process Worker singleton and the public init/get/put/wait surface.

Re-design of the reference driver/worker plumbing (reference:
``python/ray/_private/worker.py`` — ``init`` :1275, ``get`` :2636, global
``Worker`` :427). The Worker owns a :class:`CoreRuntime`; in single-process
mode that is a :class:`LocalRuntime`, in cluster mode a ``ClusterRuntime``
connected to this node's daemon.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime.interface import CoreRuntime

_global_lock = threading.Lock()
_global_worker: Optional["Worker"] = None


class Worker:
    def __init__(self, core: CoreRuntime, mode: str, namespace: str = "default"):
        self.core = core
        self.mode = mode  # "local" | "driver" | "worker"
        self.namespace = namespace
        self.session_name = f"session_{os.getpid()}"


def global_worker() -> Worker:
    w = _global_worker
    if w is None:
        # Auto-init like the reference does on first API use.
        init()
        w = _global_worker
        assert w is not None
    return w


def global_worker_or_none() -> Optional[Worker]:
    return _global_worker


def is_initialized() -> bool:
    return _global_worker is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_gpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    _system_config: Optional[Dict[str, Any]] = None,
    **kwargs,
):
    """Initialize the runtime.

    ``address=None`` starts an in-process runtime (or a local cluster when
    ``RAY_TPU_START_CLUSTER=1``); ``address="host:port"`` connects to an
    existing cluster's control plane; ``address="auto"`` discovers one.
    """
    global _global_worker
    with _global_lock:
        if _global_worker is not None:
            if ignore_reinit_error:
                return RuntimeContextInfo(_global_worker)
            raise RuntimeError(
                "ray_tpu.init() has already been called. "
                "Pass ignore_reinit_error=True to ignore.")

        from ray_tpu._private.config import GLOBAL_CONFIG

        GLOBAL_CONFIG.initialize(_system_config)

        if address is None and os.environ.get("RAY_TPU_ADDRESS"):
            address = os.environ["RAY_TPU_ADDRESS"]
        if address is not None and address.startswith("ray://"):
            # Remote-driver scheme (reference: Ray Client,
            # util/client/server/server.py:96): connect to the head's
            # driver PROXY over one framed-TCP endpoint — the driver
            # needs no reachability to the GCS, node managers, or
            # workers. Start the proxy with
            # ``python -m ray_tpu._private.client_proxy --address <gcs>``.
            from ray_tpu._private.client_proxy import ProxyRuntime

            core = ProxyRuntime(address[len("ray://"):],
                                namespace=namespace or "default")
            _global_worker = Worker(core, "client", namespace or "default")
            atexit.register(shutdown)
            return RuntimeContextInfo(_global_worker)
        if address == "auto":
            from ray_tpu.scripts.cli import _auto_address

            try:
                address = _auto_address()
            except SystemExit:  # CLI helper; re-raise catchably here
                raise ConnectionError(
                    "address='auto' found no running cluster: start a head "
                    "node or set RAY_TPU_ADDRESS") from None

        if address is None:
            if num_cpus is None:
                num_cpus = os.cpu_count() or 1
            from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

            if num_tpus is None:
                num_tpus = TPUAcceleratorManager.detect_num_chips()
            res = dict(resources or {})
            if num_gpus:
                res["GPU"] = float(num_gpus)
            from ray_tpu._private.runtime.local import LocalRuntime

            core: CoreRuntime = LocalRuntime(
                num_cpus=num_cpus, num_tpus=num_tpus, resources=res)
            mode = "local"
        else:
            from ray_tpu._private.runtime.cluster import ClusterRuntime

            core = ClusterRuntime.connect(address, namespace=namespace or "default")
            mode = "driver"

        _global_worker = Worker(core, mode, namespace or "default")
        atexit.register(shutdown)
        return RuntimeContextInfo(_global_worker)


class RuntimeContextInfo:
    """Value returned by init(); mirrors the reference's ClientContext dict-ish."""

    def __init__(self, worker: Worker):
        self.worker = worker
        self.address_info = {"node_id": getattr(worker.core, "node_id", None)}

    def __getitem__(self, k):
        return self.address_info[k]

    def disconnect(self):
        shutdown()


def shutdown():
    global _global_worker
    with _global_lock:
        w = _global_worker
        if w is None:
            return
        _global_worker = None
    try:
        w.core.shutdown()
    except Exception:
        pass


# ---------------------------------------------------------------- public ops
def put(value: Any, *, _owner=None) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed.")
    return global_worker().core.put(value, _owner)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *,
        timeout: Optional[float] = None):
    from ray_tpu.dag import CompiledDAGRef

    if isinstance(refs, CompiledDAGRef):
        # Compiled-DAG results live in channels, not the object store
        # (reference: ray.get on a CompiledDAGRef).
        return refs.get(timeout=timeout)
    is_single = isinstance(refs, ObjectRef)
    if is_single:
        refs = [refs]
    refs = list(refs)
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(
                f"get() expects ObjectRef or list of ObjectRefs, got {type(r)}")
    values = global_worker().core.get(refs, timeout)
    return values[0] if is_single else values


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs.")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() expects a list of unique ObjectRefs.")
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns ({num_returns}) exceeds number of refs ({len(refs)})")
    if num_returns <= 0:
        raise ValueError("num_returns must be > 0")
    return global_worker().core.wait(refs, num_returns, timeout, fetch_local)


def kill(actor, *, no_restart: bool = True):
    from ray_tpu.actor import ActorHandle

    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle.")
    global_worker().core.kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    from ray_tpu._private.object_ref import ObjectRefGenerator

    if isinstance(ref, ObjectRefGenerator):
        # Cancelling a streaming generator cancels its producing task; the
        # consumer surfaces the stored TaskCancelledError past the last
        # produced item (reference: cancel accepts the stream handle).
        ref = ref._length_ref
    if not isinstance(ref, ObjectRef):
        raise TypeError("cancel() expects an ObjectRef.")
    global_worker().core.cancel(ref, force, recursive)


def get_actor(name: str, namespace: Optional[str] = None):
    from ray_tpu.actor import ActorHandle

    actor_id, cls, options = global_worker().core.get_named_actor(name, namespace)
    return ActorHandle._from_actor_id(actor_id, cls, options)


def list_named_actors(all_namespaces: bool = False):
    return global_worker().core.list_named_actors(all_namespaces)


def nodes() -> List[Dict[str, Any]]:
    return global_worker().core.nodes()


def cluster_resources() -> Dict[str, float]:
    return global_worker().core.cluster_resources()


def available_resources() -> Dict[str, float]:
    return global_worker().core.available_resources()
