"""Object serialization: cloudpickle + pickle-5 out-of-band buffers.

Re-design of the reference serializer (reference: ``python/ray/_private/
serialization.py`` + the vendored cloudpickle fork): values are pickled with
protocol 5; large contiguous buffers (numpy arrays, jax host arrays, bytes)
are split out as zero-copy out-of-band buffers so they can be written straight
into the shared-memory store without an extra copy. ``ObjectRef`` instances
nested inside a value are recorded so the owner can track borrowed references.

Wire format of a serialized object:
    [u32 meta_len][meta msgpack][u32 nbuf][u64 len_i ...][buf_0][buf_1]...
meta = {"pickle": <bytes>, "refs": [ref binaries], "error": bool}
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

from ray_tpu import exceptions
from ray_tpu._private.ids import ObjectID


class SerializedObject:
    """A pickled value plus its out-of-band buffers and contained ObjectRefs."""

    __slots__ = ("pickled", "buffers", "contained_refs", "is_error")

    def __init__(self, pickled: bytes, buffers: List[pickle.PickleBuffer],
                 contained_refs: List[bytes], is_error: bool):
        self.pickled = pickled
        self.buffers = buffers
        self.contained_refs = contained_refs
        self.is_error = is_error

    def total_bytes(self) -> int:
        n = len(self.pickled)
        for b in self.buffers:
            n += memoryview(b).nbytes
        return n

    def to_bytes(self) -> bytes:
        views = [memoryview(b).cast("B") for b in self.buffers]
        header = struct.pack(
            "<IBI", len(self.pickled), 1 if self.is_error else 0, len(views)
        )
        parts = [header, struct.pack("<I", len(self.contained_refs))]
        for r in self.contained_refs:
            parts.append(struct.pack("<I", len(r)))
            parts.append(r)
        for v in views:
            parts.append(struct.pack("<Q", v.nbytes))
        parts.append(self.pickled)
        parts.extend(views)
        return b"".join(parts)

    def wire_size(self) -> int:
        """Exact byte length ``to_bytes``/``to_parts`` will produce."""
        n = struct.calcsize("<IBI") + 4
        for r in self.contained_refs:
            n += 4 + len(r)
        n += 8 * len(self.buffers) + len(self.pickled)
        for b in self.buffers:
            n += memoryview(b).nbytes
        return n

    def to_parts(self, prefix: bytes = b"") -> List[Any]:
        """The wire encoding as a list of buffers (no join): feed to
        ``os.writev`` so large out-of-band buffers are copied exactly once,
        kernel-side, into the destination (shm segment)."""
        views = [memoryview(b).cast("B") for b in self.buffers]
        parts: List[Any] = [prefix] if prefix else []
        parts.append(struct.pack(
            "<IBI", len(self.pickled), 1 if self.is_error else 0,
            len(views)))
        parts.append(struct.pack("<I", len(self.contained_refs)))
        for r in self.contained_refs:
            parts.append(struct.pack("<I", len(r)))
            parts.append(r)
        for v in views:
            parts.append(struct.pack("<Q", v.nbytes))
        parts.append(self.pickled)
        parts.extend(views)
        return parts

    @staticmethod
    def parse(data) -> "SerializedObject":
        mv = memoryview(data)
        plen, is_err, nbuf = struct.unpack_from("<IBI", mv, 0)
        off = struct.calcsize("<IBI")
        (nrefs,) = struct.unpack_from("<I", mv, off)
        off += 4
        refs = []
        for _ in range(nrefs):
            (rlen,) = struct.unpack_from("<I", mv, off)
            off += 4
            refs.append(bytes(mv[off : off + rlen]))
            off += rlen
        blens = []
        for _ in range(nbuf):
            (blen,) = struct.unpack_from("<Q", mv, off)
            off += 8
            blens.append(blen)
        pickled = bytes(mv[off : off + plen])
        off += plen
        buffers = []
        for blen in blens:
            buffers.append(pickle.PickleBuffer(mv[off : off + blen]))
            off += blen
        return SerializedObject(pickled, buffers, refs, bool(is_err))


_OOB_THRESHOLD = 4096  # buffers smaller than this are kept in-band


class _RefPickler(cloudpickle.CloudPickler):
    """Module-level pickler (a per-call class definition costs ~10 us of
    type creation on the task hot path). ``contained`` collects the
    binaries of ObjectRefs nested in the value."""

    def __init__(self, file, buffer_callback, contained):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)
        self._contained = contained

    def persistent_id(self, obj):  # noqa: N802 (pickle API)
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            self._contained.append(obj.binary())
            return ("ray_tpu.ObjectRef", obj.binary(), obj.owner_address())
        return None


class _RefUnpickler(pickle.Unpickler):
    def __init__(self, file, buffers, ref_deserializer):
        super().__init__(file, buffers=buffers)
        self._ref_deserializer = ref_deserializer

    def persistent_load(self, pid):  # noqa: N802 (pickle API)
        tag, binary, owner = pid
        if tag != "ray_tpu.ObjectRef":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        from ray_tpu._private.object_ref import ObjectRef

        ref = ObjectRef(ObjectID(binary), owner_address=owner)
        if self._ref_deserializer is not None:
            self._ref_deserializer(ref)
        return ref


class Serializer:
    """Pickles/unpickles values, tracking nested ObjectRefs.

    A fresh ``contained`` list is captured per call, so one Serializer instance
    is safe to share within a worker (calls are not recursive across threads
    holding state: state is per-invocation).
    """

    def __init__(self, ref_deserializer=None):
        # Called with an ObjectRef binary when a ref is deserialized, so the
        # runtime can register a borrowed reference.
        self.ref_deserializer = ref_deserializer

    def serialize(self, value: Any) -> SerializedObject:
        import io

        contained: List[bytes] = []
        buffers: List[pickle.PickleBuffer] = []

        def buffer_callback(pb: pickle.PickleBuffer) -> bool:
            if memoryview(pb).nbytes < _OOB_THRESHOLD:
                return True  # keep small buffers in-band
            buffers.append(pb)
            return False

        is_error = isinstance(value, exceptions.RayTaskError) or isinstance(
            value, exceptions.RayTpuError
        )
        f = io.BytesIO()
        _RefPickler(f, buffer_callback, contained).dump(value)
        return SerializedObject(f.getvalue(), buffers, contained, is_error)

    def deserialize(self, s: SerializedObject) -> Any:
        import io

        return _RefUnpickler(io.BytesIO(s.pickled), s.buffers,
                             self.ref_deserializer).load()


def serialize_error(exc: BaseException, function_name: str, task_id=None) -> Any:
    """Wrap an executor-side exception as a storable RayTaskError value."""
    if isinstance(exc, exceptions.RayTaskError):
        return exc
    return exceptions.RayTaskError.from_exception(exc, function_name, task_id)
