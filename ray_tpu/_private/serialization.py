"""Object serialization: cloudpickle + pickle-5 out-of-band buffers.

Re-design of the reference serializer (reference: ``python/ray/_private/
serialization.py`` + the vendored cloudpickle fork): values are pickled with
protocol 5; large contiguous buffers (numpy arrays, jax host arrays, bytes)
are split out as zero-copy out-of-band buffers so they can be written straight
into the shared-memory store without an extra copy. ``ObjectRef`` instances
nested inside a value are recorded so the owner can track borrowed references.

Wire format of a serialized object:
    [u32 meta_len][meta msgpack][u32 nbuf][u64 len_i ...][buf_0][buf_1]...
meta = {"pickle": <bytes>, "refs": [ref binaries], "error": bool}
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

from ray_tpu import exceptions
from ray_tpu._private.ids import ObjectID


class SerializedObject:
    """A pickled value plus its out-of-band buffers and contained ObjectRefs."""

    __slots__ = ("pickled", "buffers", "contained_refs", "is_error")

    def __init__(self, pickled: bytes, buffers: List[pickle.PickleBuffer],
                 contained_refs: List[bytes], is_error: bool):
        self.pickled = pickled
        self.buffers = buffers
        self.contained_refs = contained_refs
        self.is_error = is_error

    def total_bytes(self) -> int:
        n = len(self.pickled)
        for b in self.buffers:
            n += memoryview(b).nbytes
        return n

    def to_bytes(self) -> bytes:
        views = [memoryview(b).cast("B") for b in self.buffers]
        header = struct.pack(
            "<IBI", len(self.pickled), 1 if self.is_error else 0, len(views)
        )
        parts = [header, struct.pack("<I", len(self.contained_refs))]
        for r in self.contained_refs:
            parts.append(struct.pack("<I", len(r)))
            parts.append(r)
        for v in views:
            parts.append(struct.pack("<Q", v.nbytes))
        parts.append(self.pickled)
        parts.extend(views)
        return b"".join(parts)

    def write_into(self, buf: memoryview) -> int:
        data = self.to_bytes()
        buf[: len(data)] = data
        return len(data)

    @staticmethod
    def parse(data) -> "SerializedObject":
        mv = memoryview(data)
        plen, is_err, nbuf = struct.unpack_from("<IBI", mv, 0)
        off = struct.calcsize("<IBI")
        (nrefs,) = struct.unpack_from("<I", mv, off)
        off += 4
        refs = []
        for _ in range(nrefs):
            (rlen,) = struct.unpack_from("<I", mv, off)
            off += 4
            refs.append(bytes(mv[off : off + rlen]))
            off += rlen
        blens = []
        for _ in range(nbuf):
            (blen,) = struct.unpack_from("<Q", mv, off)
            off += 8
            blens.append(blen)
        pickled = bytes(mv[off : off + plen])
        off += plen
        buffers = []
        for blen in blens:
            buffers.append(pickle.PickleBuffer(mv[off : off + blen]))
            off += blen
        return SerializedObject(pickled, buffers, refs, bool(is_err))


_OOB_THRESHOLD = 4096  # buffers smaller than this are kept in-band


class Serializer:
    """Pickles/unpickles values, tracking nested ObjectRefs.

    A fresh ``contained`` list is captured per call, so one Serializer instance
    is safe to share within a worker (calls are not recursive across threads
    holding state: state is per-invocation).
    """

    def __init__(self, ref_deserializer=None):
        # Called with an ObjectRef binary when a ref is deserialized, so the
        # runtime can register a borrowed reference.
        self.ref_deserializer = ref_deserializer

    def serialize(self, value: Any) -> SerializedObject:
        from ray_tpu._private.object_ref import ObjectRef

        contained: List[bytes] = []
        buffers: List[pickle.PickleBuffer] = []

        def buffer_callback(pb: pickle.PickleBuffer) -> bool:
            if memoryview(pb).nbytes < _OOB_THRESHOLD:
                return True  # keep small buffers in-band
            buffers.append(pb)
            return False

        is_error = isinstance(value, exceptions.RayTaskError) or isinstance(
            value, exceptions.RayTpuError
        )

        class _Pickler(cloudpickle.CloudPickler):
            def persistent_id(self, obj):  # noqa: N802 (pickle API)
                if isinstance(obj, ObjectRef):
                    contained.append(obj.binary())
                    return ("ray_tpu.ObjectRef", obj.binary(), obj.owner_address())
                return None

        import io

        f = io.BytesIO()
        p = _Pickler(f, protocol=5, buffer_callback=buffer_callback)
        p.dump(value)
        return SerializedObject(f.getvalue(), buffers, contained, is_error)

    def deserialize(self, s: SerializedObject) -> Any:
        serializer = self

        class _Unpickler(pickle.Unpickler):
            def persistent_load(self, pid):  # noqa: N802 (pickle API)
                tag, binary, owner = pid
                if tag != "ray_tpu.ObjectRef":
                    raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
                from ray_tpu._private.object_ref import ObjectRef

                ref = ObjectRef(ObjectID(binary), owner_address=owner)
                if serializer.ref_deserializer is not None:
                    serializer.ref_deserializer(ref)
                return ref

        import io

        up = _Unpickler(io.BytesIO(s.pickled), buffers=s.buffers)
        return up.load()


def serialize_error(exc: BaseException, function_name: str, task_id=None) -> Any:
    """Wrap an executor-side exception as a storable RayTaskError value."""
    if isinstance(exc, exceptions.RayTaskError):
        return exc
    return exceptions.RayTaskError.from_exception(exc, function_name, task_id)
