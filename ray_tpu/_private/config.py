"""Framework configuration: typed defaults, env overrides, JSON system-config.

Re-design of the reference config system (reference: ``src/ray/common/ray_config_def.h``
— 220 ``RAY_CONFIG(type, name, default)`` macros, overridable via env ``RAY_<name>``
or the ``_system_config`` JSON passed to ``ray.init``). Here a config entry is a
dataclass field; overrides are resolved at access time in priority order:

    1. explicit ``_system_config`` dict passed to :func:`ray_tpu.init`
    2. environment variable ``RAY_TPU_<name>`` (and ``RAY_<name>`` for parity)
    3. the coded default

Booleans accept 0/1/true/false; everything else is parsed with the field's type.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict


@dataclasses.dataclass
class _ConfigDefaults:
    # --- object store -----------------------------------------------------
    # Objects larger than this are promoted from the in-process memory store
    # to the shared-memory store (reference: core_worker store providers,
    # 100KB threshold).
    max_direct_call_object_size: int = 100 * 1024
    # Default shm store size as a fraction of system memory if not given.
    object_store_memory_fraction: float = 0.3
    object_store_memory: int = 0  # 0 = auto from fraction, capped below
    object_store_memory_cap: int = 20 * 2**30
    # Chunk size for node-to-node object transfer.
    object_transfer_chunk_size: int = 64 * 2**20
    # Seconds an unreferenced primary copy stays before eviction is allowed.
    object_store_full_delay_ms: int = 10

    # --- scheduler --------------------------------------------------------
    # Hybrid policy: pack onto nodes until utilization crosses this threshold,
    # then spread (reference: hybrid_scheduling_policy.cc:99 — 0.5).
    scheduler_spread_threshold: float = 0.5
    # Max tasks in flight per lease (lease reuse).
    max_tasks_in_flight_per_worker: int = 10
    worker_lease_timeout_ms: int = 500

    # --- worker pool ------------------------------------------------------
    num_workers_soft_limit: int = 0  # 0 = num_cpus
    worker_register_timeout_seconds: int = 60
    idle_worker_killing_time_threshold_ms: int = 1000
    enable_worker_prestart: bool = True

    # --- health / failure detection --------------------------------------
    # Reference: gcs_health_check_manager.h:45-62.
    health_check_initial_delay_ms: int = 5000
    health_check_period_ms: int = 3000
    health_check_timeout_ms: int = 10000
    health_check_failure_threshold: int = 5

    # --- retries / recovery ----------------------------------------------
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    lineage_pinning_enabled: bool = True
    max_lineage_bytes: int = 1 * 2**30

    # --- rpc --------------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    rpc_call_timeout_s: float = 120.0
    rpc_retry_base_delay_ms: int = 100
    rpc_retry_max_delay_ms: int = 5000
    rpc_max_retries: int = 5
    # Deterministic fault injection, format "method:prob[,method:prob...]"
    # (reference: src/ray/rpc/rpc_chaos.cc, env RAY_testing_rpc_failure).
    testing_rpc_failure: str = ""

    # --- gcs --------------------------------------------------------------
    gcs_storage_path: str = ""  # "" = in-memory; path = file-backed persistence
    gcs_pubsub_poll_timeout_s: float = 30.0

    # --- task events / tracing -------------------------------------------
    task_events_report_interval_ms: int = 1000
    task_events_max_buffer_size: int = 10000
    enable_timeline: bool = True

    # --- metrics ----------------------------------------------------------
    metrics_report_interval_ms: int = 5000

    # --- memory monitor ---------------------------------------------------
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 250

    # --- TPU --------------------------------------------------------------
    # Treat TPU chips as first-class schedulable resources.
    tpu_chips_per_host_default: int = 4
    # ICI slice label prefix used for slice-aware placement groups.
    tpu_slice_resource_prefix: str = "TPU-slice"


_TRUE = {"1", "true", "True", "TRUE", "yes", "on"}
_FALSE = {"0", "false", "False", "FALSE", "no", "off"}


class RayTpuConfig:
    """Accessor resolving (system_config > env > default) per field."""

    def __init__(self):
        self._defaults = _ConfigDefaults()
        self._system_config: Dict[str, Any] = {}
        self._fields = {f.name: f.type for f in dataclasses.fields(_ConfigDefaults)}

    def initialize(self, system_config: Dict[str, Any] | str | None):
        if system_config is None:
            system_config = {}
        if isinstance(system_config, str):
            system_config = json.loads(system_config) if system_config else {}
        unknown = set(system_config) - set(self._fields)
        if unknown:
            raise ValueError(f"Unknown _system_config keys: {sorted(unknown)}")
        self._system_config = dict(system_config)

    def _coerce(self, name: str, raw: Any) -> Any:
        default = getattr(self._defaults, name)
        ty = type(default)
        if isinstance(raw, ty) and not (ty is int and isinstance(raw, bool)):
            return raw
        if ty is bool:
            s = str(raw)
            if s in _TRUE:
                return True
            if s in _FALSE:
                return False
            raise ValueError(f"Cannot parse bool config {name}={raw!r}")
        return ty(raw)

    def __getattr__(self, name: str) -> Any:
        fields = object.__getattribute__(self, "_fields")
        if name not in fields:
            raise AttributeError(name)
        sysconf = object.__getattribute__(self, "_system_config")
        if name in sysconf:
            return self._coerce(name, sysconf[name])
        for prefix in ("RAY_TPU_", "RAY_"):
            env = os.environ.get(prefix + name)
            if env is not None:
                return self._coerce(name, env)
        return getattr(object.__getattribute__(self, "_defaults"), name)

    def dump(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in self._fields}


GLOBAL_CONFIG = RayTpuConfig()
