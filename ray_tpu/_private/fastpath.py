"""Binary task plane: length-framed protobuf over raw TCP.

Reference rationale: the reference's hot path is a C++ gRPC stack whose
per-call overhead is tens of microseconds (``core_worker.cc:2485`` task
submission, ``direct_task_transport``); Python gRPC's per-unary-call cost
(channel dispatch, completion queue hops, HTTP/2 framing) is 300-500 us —
an order of magnitude of pure overhead on a no-op task. This module is the
redesign: one persistent TCP connection per (caller, worker) pair carrying
length-framed protobuf messages with request-id multiplexing, so many
in-flight tasks pipeline on one socket. The protobuf *messages* stay
identical to the gRPC ones (``PushTaskRequest``/``PushTaskResult``); only
the transport changes. gRPC remains for everything that is not
latency-critical (control plane, streaming pulls) and as the fallback when
the fastpath listener is unreachable.

Frame layout (little-endian):
    [u32 req_id][u8 kind][u32 len][len bytes payload]
Replies echo ``req_id``; ``kind`` distinguishes request types so one
connection can carry several RPCs (task push, object put).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<IBI")

# Frame kinds. A reply's kind is the request's kind | 0x80; KIND_ERR
# replies carry a utf-8 error message (handler raised server-side).
KIND_PUSH_TASK = 1
KIND_PUSH_BATCH = 2
KIND_PUT_BATCH = 3   # node object plane: PutObjectBatch
KIND_ERR = 0x7F


def call_proto(address: str, kind: int, request, reply_cls, timeout: float):
    """One protobuf round-trip over the fastpath plane.

    Returns ``("ok", reply)``, ``("no_client", None)`` when no fastpath
    client is reachable (callers fall back to gRPC), or
    ``("error", None)`` when the connection died mid-call — the request
    MAY have executed (same ambiguity as a failed gRPC call); callers
    must apply their own retry policy, not blindly resend.
    """
    if not address:
        return "no_client", None
    fc = get_client(address)
    if fc is None:
        return "no_client", None
    try:
        data = fc.call(kind, request.SerializeToString(), timeout=timeout)
    except Exception:  # noqa: BLE001 — connection/timeout
        return "error", None
    reply = reply_cls()
    reply.ParseFromString(data)
    return "ok", reply
KIND_REPLY_BIT = 0x80

_MAX_FRAME = 1 << 31


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes or return None on EOF."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None
        got += r
    return bytes(buf)


class FastClient:
    """One multiplexed connection to a FastServer.

    ``call()`` is thread-safe: concurrent callers pipeline frames on the
    single socket; a dedicated reader thread resolves replies to futures
    by request id. A broken connection fails every pending call with
    ``ConnectionError`` and marks the client dead (callers fall back to
    gRPC and drop the client from their cache).
    """

    CONNECT_TIMEOUT_S = 5.0

    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection(
            (host, int(port)), timeout=self.CONNECT_TIMEOUT_S)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 0
        self._dead = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"fastpath-read-{address}")
        self._reader.start()

    @property
    def dead(self) -> bool:
        return self._dead

    def call(self, kind: int, payload: bytes,
             timeout: Optional[float] = None) -> bytes:
        if self._dead:
            raise ConnectionError("fastpath connection is closed")
        fut: Future = Future()
        with self._pending_lock:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            req_id = self._next_id
            self._pending[req_id] = fut
        frame = _HDR.pack(req_id, kind, len(payload))
        try:
            with self._send_lock:
                self._sock.sendall(frame)
                self._sock.sendall(payload)
        except OSError as e:
            self._fail(e)
            raise ConnectionError(f"fastpath send failed: {e}") from None
        try:
            return fut.result(timeout=timeout)
        finally:
            with self._pending_lock:
                self._pending.pop(req_id, None)

    def _read_loop(self):
        try:
            while True:
                hdr = _recv_exact(self._sock, _HDR.size)
                if hdr is None:
                    raise ConnectionError("fastpath peer closed")
                req_id, kind, length = _HDR.unpack(hdr)
                if length > _MAX_FRAME:
                    raise ConnectionError(f"oversized frame ({length})")
                payload = _recv_exact(self._sock, length)
                if payload is None:
                    raise ConnectionError("fastpath peer closed mid-frame")
                with self._pending_lock:
                    fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    if kind == (KIND_ERR | KIND_REPLY_BIT):
                        fut.set_exception(RuntimeError(
                            f"fastpath handler error: "
                            f"{payload.decode('utf-8', 'replace')}"))
                    else:
                        fut.set_result(payload)
        except Exception as e:  # noqa: BLE001 — any break kills the client
            self._fail(e)

    def _fail(self, exc: BaseException):
        self._dead = True
        with self._pending_lock:
            pending, self._pending = dict(self._pending), {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(
                    ConnectionError(f"fastpath connection lost: {exc}"))
        try:
            # shutdown() before close(): the reader thread's in-flight
            # recv holds the open file description, so a bare close()
            # never sends FIN and the peer's connection lingers forever.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self):
        self._fail(ConnectionError("closed"))


class FastServer:
    """Accepts fastpath connections and dispatches frames to a handler.

    ``handler(kind, payload) -> reply_bytes`` runs on a shared thread pool
    — a slow request must not block other pipelined requests on the same
    connection (ordered actor pushes park until their sequence turn, the
    same reason the gRPC server ran a wide pool).
    """

    def __init__(self, handler: Callable[[int, bytes], bytes],
                 host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 128):
        self._handler = handler
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self.address = f"{host}:{self.port}"
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="fastpath-srv")
        self._conns: list = []
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"fastpath-accept-{self.port}").start()

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True, name="fastpath-conn").start()

    def _conn_loop(self, conn: socket.socket):
        send_lock = threading.Lock()
        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                req_id, kind, length = _HDR.unpack(hdr)
                if length > _MAX_FRAME:
                    return
                payload = _recv_exact(conn, length)
                if payload is None:
                    return
                self._pool.submit(self._dispatch, conn, send_lock, req_id,
                                  kind, payload)
        except OSError:
            return
        finally:
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, send_lock, req_id: int, kind: int,
                  payload: bytes):
        try:
            reply = self._handler(kind, payload)
            reply_kind = kind | KIND_REPLY_BIT
        except Exception as e:  # noqa: BLE001 — handler bug: the caller
            # must fail fast, not wait out its (potentially huge) push
            # timeout on a frame that will never be answered.
            logger.exception("fastpath handler failed (kind=%d)", kind)
            reply = f"{type(e).__name__}: {e}".encode()
            reply_kind = KIND_ERR | KIND_REPLY_BIT
        frame = _HDR.pack(req_id, reply_kind, len(reply))
        try:
            with send_lock:
                conn.sendall(frame)
                conn.sendall(reply)
        except OSError:
            pass

    def close(self):
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)  # wake the reader thread
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False)


_clients: Dict[str, FastClient] = {}
_clients_lock = threading.Lock()


def get_client(address: str) -> Optional[FastClient]:
    """Cached client for a fastpath address, or None when unreachable.

    Dead clients are dropped and re-dialed once; a connect failure returns
    None so callers fall back to gRPC (and retry the fastpath on the next
    call — the worker may still be starting its listener).
    """
    if not address:
        return None
    with _clients_lock:
        client = _clients.get(address)
        if client is not None and not client.dead:
            return client
        _clients.pop(address, None)
    try:
        client = FastClient(address)
    except OSError:
        return None
    with _clients_lock:
        existing = _clients.get(address)
        if existing is not None and not existing.dead:
            client.close()
            return existing
        _clients[address] = client
    return client


def drop_client(address: str) -> None:
    with _clients_lock:
        client = _clients.pop(address, None)
    if client is not None:
        client.close()
