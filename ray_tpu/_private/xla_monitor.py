"""Per-process XLA observability: the compile/retrace/cost/capture plane.

The PR-1 observability stack (TSDB, push plane, dashboard) stops at the
Python layer; this module makes the XLA layer itself a first-class
surface, feeding the same planes:

* :func:`instrument` wraps ``jax.jit`` for every framework-owned entry
  point. Dispatch goes through jax's AOT path (``lower().compile()``)
  keyed by the call signature, so each compile is observed exactly once
  with its true wall time — no double compilation, no guessing. A
  **retrace detector** flags a second compile of the same logical
  function with a new shape/dtype signature (``shape_policy`` declares
  which shape growth is legitimate: the serve engine's power-of-two
  bucketed prefill stays silent; arbitrary shape churn fires
  ``ray_tpu_xla_retraces_total`` and logs the signature diff).
* after each compile the executable's ``cost_analysis()`` (FLOPs, bytes
  accessed) is harvested into a per-process **program registry**,
  persisted best-effort in the GCS KV under ``__xla_programs__`` and
  exported as gauges. Call sites that measure real step/tick wall time
  feed it back via :meth:`InstrumentedJit.note_execution`, yielding
  achieved-FLOPs / achieved-HBM-bandwidth / MFU gauges with zero
  estimation; absent an explicit measurement the wrapper falls back to
  call cadence (honest in loops that sync per step).
* :func:`sample_device_memory` publishes per-device ``memory_stats()``
  vitals (graceful no-op on CPU, and never *imports* jax into a process
  that doesn't already hold devices — a fresh import on a TPU host would
  steal the chips from the workers).
* a **capture listener** subscribes to the GCS ``PROFILE`` pubsub
  channel; an on-demand command (CLI ``ray-tpu profile capture``,
  dashboard ``/api/v1/profile/capture``) makes every XLA-active process
  on the target node run ``jax.profiler`` trace capture for N seconds,
  write the trace under the session dir and register it in the GCS KV
  under ``__profiles__``.

Everything degrades gracefully on CPU (cost analysis works, memory
stats return None, profiler traces still capture), so tier-1 exercises
the full plane under ``JAX_PLATFORMS=cpu``. ``RAY_TPU_XLA_MONITOR=0``
turns the wrapper into a transparent ``jax.jit``.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

PROFILE_CHANNEL = "PROFILE"
PROFILE_KV_NS = "__profiles__"
PROGRAM_KV_NS = "__xla_programs__"

# bf16/fp16 peak FLOPs per chip by device kind (prefix match, like the
# HBM table in bench_serve.py). MFU is only emitted when the kind is
# known; CPU reports achieved FLOPs/bandwidth without a utilization.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e
}


def _enabled() -> bool:
    return os.environ.get("RAY_TPU_XLA_MONITOR", "1") != "0"


def session_dir() -> str:
    return os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu_state")


# --------------------------------------------------------------- connection
# Where this process's XLA telemetry goes: set by the same call sites
# that start the metrics pusher (driver/worker runtime, node manager).
_state_lock = threading.Lock()
_gcs_address: Optional[str] = None
_node_id: Optional[str] = None
_conn_refs: Dict[str, int] = {}               # address -> connect() count
_listeners: Dict[str, threading.Event] = {}   # address -> stop event
_maintenance_stop: Optional[threading.Event] = None
# (ns, key) -> [payload, tries]; insertion-ordered for bounded eviction.
_pending_kv: OrderedDict = OrderedDict()
_programs: Dict[str, "_ProgramRecord"] = {}
_capture_lock = threading.Lock()              # jax.profiler can't nest


def connect(gcs_address: str, node_id: Optional[str] = None) -> None:
    """Record where XLA telemetry for this process should land. The
    profile-capture listener starts lazily at the first instrumented
    compile — processes that never touch XLA pay nothing. Refcounted:
    each connect() is balanced by a disconnect() (mirrors the metrics
    pusher's claims, so one driver's shutdown can't silence a
    co-resident node manager's capture plane)."""
    global _gcs_address, _node_id
    if not gcs_address or not _enabled():
        return
    with _state_lock:
        _conn_refs[gcs_address] = _conn_refs.get(gcs_address, 0) + 1
        _gcs_address = gcs_address
        if node_id:
            _node_id = node_id
    if _programs:
        # XLA already active in this process: bring the planes up now.
        _ensure_listener(gcs_address)
        _ensure_maintenance()


def disconnect(gcs_address: str) -> None:
    """Drop one component's claim on the address; the listener stops
    only when the last claimant disconnects."""
    global _gcs_address
    stop = None
    with _state_lock:
        n = _conn_refs.get(gcs_address, 0) - 1
        if n > 0:
            _conn_refs[gcs_address] = n
            return
        _conn_refs.pop(gcs_address, None)
        stop = _listeners.pop(gcs_address, None)
        if _gcs_address == gcs_address:
            _gcs_address = next(iter(_conn_refs), None)
    if stop is not None:
        stop.set()


def stop_all() -> None:
    """Stop listener/maintenance threads (sequential test clusters)."""
    global _maintenance_stop
    with _state_lock:
        stops = list(_listeners.values())
        _listeners.clear()
        _conn_refs.clear()
        if _maintenance_stop is not None:
            stops.append(_maintenance_stop)
            _maintenance_stop = None
    for s in stops:
        s.set()


def _on_xla_activity() -> None:
    with _state_lock:
        address = _gcs_address
    if address:
        _ensure_listener(address)
        _ensure_maintenance()


# ----------------------------------------------------------- program registry
class _ProgramRecord:
    __slots__ = ("name", "compiles", "retraces", "signatures", "cost",
                 "compile_seconds")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.retraces = 0
        # signature key -> {"signature", "flops", "bytes_accessed", ...}
        self.signatures: Dict[Any, Dict[str, Any]] = {}
        self.cost: Optional[Dict[str, float]] = None   # latest compile's
        self.compile_seconds = 0.0


def _record(name: str) -> _ProgramRecord:
    with _state_lock:
        rec = _programs.get(name)
        if rec is None:
            rec = _programs[name] = _ProgramRecord(name)
        return rec


def program_stats(name: str) -> Optional[Dict[str, Any]]:
    """Latest compile stats for a program (bench_serve reads the
    cost-analysis bytes instead of hand-estimating HBM traffic)."""
    rec = _programs.get(name)
    if rec is None:
        return None
    out = {"name": rec.name, "compiles": rec.compiles,
           "retraces": rec.retraces,
           "compile_seconds": rec.compile_seconds,
           "signatures": len(rec.signatures)}
    if rec.cost:
        out.update(rec.cost)
    return out


def all_program_stats() -> List[Dict[str, Any]]:
    return [s for s in (program_stats(n) for n in list(_programs))
            if s is not None]


def _queue_kv(ns: str, key: str, payload: Dict[str, Any]) -> None:
    with _state_lock:
        # Keyed: a burst of compiles for one program coalesces into one
        # pending write of the latest record.
        _pending_kv[(ns, key)] = [payload, 0]
        while len(_pending_kv) > 512:   # bounded: telemetry, not truth
            _pending_kv.popitem(last=False)


def _flush_pending_kv() -> None:
    with _state_lock:
        address = _gcs_address
        batch = list(_pending_kv.items())
    if address is None or not batch:
        return
    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    gcs = rpc.get_stub("GcsService", address)
    for (ns, key), entry in batch:
        payload, tries = entry
        try:
            gcs.KvPut(pb.KvRequest(
                ns=ns, key=key, value=json.dumps(payload).encode(),
                overwrite=True), timeout=5)
        except Exception:  # noqa: BLE001 — head briefly unreachable
            with _state_lock:
                if _pending_kv.get((ns, key)) is entry:
                    if tries >= 3:
                        _pending_kv.pop((ns, key), None)
                    else:
                        entry[1] = tries + 1
            return
        with _state_lock:
            if _pending_kv.get((ns, key)) is entry:
                _pending_kv.pop((ns, key))


# ------------------------------------------------------------- signatures
def _tracer_type():
    try:
        from jax.core import Tracer
    except Exception:  # noqa: BLE001 - jax.core reshuffles across versions
        from jax._src.core import Tracer
    return Tracer


def _leaf_sig(x) -> Tuple:
    aval = getattr(x, "aval", None)
    if aval is not None:
        return (tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)))
    if isinstance(x, (bool, int, float, complex)):
        # Python scalars trace as weak-typed values: keyed by TYPE, never
        # by value, or a decode loop's position arg would recompile
        # per token.
        return (type(x).__name__, "weak")
    shape, dtype = getattr(x, "shape", None), getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype), False)
    return ("opaque", type(x).__name__)


def _fmt_sig(leaf_sigs: Sequence[Tuple]) -> str:
    parts = []
    for s in leaf_sigs:
        if isinstance(s[0], tuple):
            parts.append(f"{s[1]}[{','.join(map(str, s[0]))}]"
                         + ("w" if s[2] else ""))
        else:
            parts.append(f"{s[0]}:{s[1]}")
    return "(" + ", ".join(parts) + ")"


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _changed_dims(old: Sequence[Tuple], new: Sequence[Tuple]) -> \
        Optional[List[int]]:
    """Dims (new values) that differ between two same-structure leaf-sig
    tuples; None when the signatures differ beyond shapes (dtype/type)."""
    if len(old) != len(new):
        return None
    dims: List[int] = []
    for o, n in zip(old, new):
        if o == n:
            continue
        if not (isinstance(o[0], tuple) and isinstance(n[0], tuple)) \
                or o[1:] != n[1:] or len(o[0]) != len(n[0]):
            return None                  # dtype / structure change
        dims.extend(nd for od, nd in zip(o[0], n[0]) if od != nd)
    return dims


# --------------------------------------------------------------- the wrapper
class InstrumentedJit:
    """``jax.jit`` with compile/retrace/cost observability.

    Dispatch: per-signature AOT executables (``lower().compile()``) so
    compile events are first-class; nested calls under an outer trace
    inline through the plain jit, and any AOT failure degrades the
    wrapper to plain jit permanently (observability must never take the
    hot path down).

    ``shape_policy``:

    * ``"static"`` — the program has ONE legitimate signature; any
      second compile is a retrace.
    * ``"bucketed"`` — new signatures are expected as long as every
      changed dim is a power of two (or listed in ``allowed_dims``):
      the serve engine's bucketed prefill compiles log(N)·log(L)
      programs by design, but a stray odd shape is a real retrace.
    * ``"free"`` — compile tracking only (utility entry points that
      legitimately see arbitrary shapes).
    """

    def __init__(self, fn, name: str, shape_policy: str = "static",
                 allowed_dims: Sequence[int] = (), aot: bool = True,
                 **jit_kwargs):
        import jax

        assert shape_policy in ("static", "bucketed", "free"), shape_policy
        self.name = name
        self.shape_policy = shape_policy
        self.allowed_dims = frozenset(int(d) for d in allowed_dims)
        self._jitted = jax.jit(fn, **jit_kwargs)
        # Static args are baked into the lowered program, and the AOT
        # executable is called WITHOUT them — rather than re-deriving
        # jax's static/dynamic arg split here, those wrappers dispatch
        # through the plain jit (compile time observed as first-call
        # wall time) with the static VALUES folded into the signature
        # key so two static variants never share a cache entry.
        self._static_argnums = tuple(
            jit_kwargs.get("static_argnums") or ())
        if isinstance(jit_kwargs.get("static_argnums"), int):
            self._static_argnums = (jit_kwargs["static_argnums"],)
        names = jit_kwargs.get("static_argnames") or ()
        self._static_argnames = (names,) if isinstance(names, str) \
            else tuple(names)
        self._aot = aot and not (self._static_argnums
                                 or self._static_argnames)
        self._degraded = False
        # With donated inputs a failed dispatch may already have consumed
        # its buffers: retrying through the plain jit would hit deleted
        # arrays, so those programs re-raise and only degrade the NEXT
        # call.
        self._donates = bool(jit_kwargs.get("donate_argnums")
                             or jit_kwargs.get("donate_argnames"))
        self._compiled: Dict[Any, Any] = {}       # sig key -> executable
        self._sigs: Dict[Any, List[Tuple]] = {}   # sig key -> leaf sigs
        self._last_key: Optional[Any] = None
        # Timing state is PER WRAPPER: two engines sharing a program
        # name must not freeze or garble each other's achieved gauges.
        self._last_call: Optional[float] = None
        self._external_timing = False
        self._lock = threading.Lock()
        self._tracer = _tracer_type()

    # Anything not overridden (``lower``, ``eval_shape``, ...) behaves
    # like the underlying jit.
    def __getattr__(self, item):
        jitted = self.__dict__.get("_jitted")
        if jitted is None:
            raise AttributeError(item)
        return getattr(jitted, item)

    def _cache_size(self) -> int:
        """Compiled-program count — mirrors jax's private jit cache
        counter for signature-reuse acceptance checks."""
        if self._degraded or not self._aot or not _enabled():
            real = getattr(self._jitted, "_cache_size", None)
            return real() if real is not None else len(self._sigs)
        return len(self._compiled)

    def __call__(self, *args, **kwargs):
        if not _enabled():
            return self._jitted(*args, **kwargs)
        import jax

        leaves, treedef = jax.tree.flatten((args, kwargs))
        if any(isinstance(x, self._tracer) for x in leaves):
            # Called inside an outer trace: inline, don't observe.
            return self._jitted(*args, **kwargs)
        leaf_sigs = tuple(_leaf_sig(x) for x in leaves)
        key = (treedef, leaf_sigs, self._static_key(args, kwargs))
        self._note_cadence()
        self._last_key = key
        entry = None if self._degraded else self._compiled.get(key)
        if entry is not None:
            try:
                return entry(*args, **kwargs)
            except Exception:  # noqa: BLE001 — AOT quirk: degrade, stay up
                return self._dispatch_failed(key, args, kwargs)
        return self._compile_and_call(key, leaf_sigs, args, kwargs)

    def _static_key(self, args, kwargs) -> Tuple:
        if not (self._static_argnums or self._static_argnames):
            return ()
        return (tuple(repr(args[i]) for i in self._static_argnums
                      if i < len(args)),
                tuple((k, repr(kwargs[k])) for k in self._static_argnames
                      if k in kwargs))

    def _dispatch_failed(self, key, args, kwargs):
        """An AOT executable failed: degrade the wrapper (plain jit from
        here on) and evict the executable so no path retries it. Donated
        inputs may already be consumed — re-raise rather than touch
        deleted buffers."""
        self._degraded = True
        self._compiled.pop(key, None)
        if self._donates:
            logger.exception(
                "xla_monitor: AOT dispatch of %r failed with donated "
                "inputs; degrading to plain jit for subsequent calls",
                self.name)
            raise
        logger.exception("xla_monitor: AOT dispatch of %r failed; "
                         "degrading to plain jit", self.name)
        return self._jitted(*args, **kwargs)

    # ------------------------------------------------------------ compile
    def _compile_and_call(self, key, leaf_sigs, args, kwargs):
        with self._lock:
            entry = self._compiled.get(key)
            if entry is not None:
                pass  # lost the race: dispatch below
            elif self._degraded or not self._aot:
                t0 = time.perf_counter()
                out = self._jitted(*args, **kwargs)
                # First-call wall time (compile + one execution): the
                # honest proxy when the AOT path is unavailable.
                if key not in self._sigs:
                    self._observe_compile(key, leaf_sigs,
                                          time.perf_counter() - t0,
                                          cost=None)
                return out
            else:
                try:
                    t0 = time.perf_counter()
                    lowered = self._jitted.lower(*args, **kwargs)
                    entry = lowered.compile()
                    dt = time.perf_counter() - t0
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "xla_monitor: AOT compile of %r failed; "
                        "degrading to plain jit", self.name)
                    self._degraded = True
                    return self._jitted(*args, **kwargs)
                self._compiled[key] = entry
                self._observe_compile(key, leaf_sigs, dt,
                                      cost=_harvest_cost(entry))
        try:
            return entry(*args, **kwargs)
        except Exception:  # noqa: BLE001
            return self._dispatch_failed(key, args, kwargs)

    def _observe_compile(self, key, leaf_sigs, seconds: float,
                         cost: Optional[Dict[str, float]]) -> None:
        from ray_tpu._private import metrics_defs as mdefs

        rec = _record(self.name)
        tags = {"program": self.name}
        retrace_from = self._detect_retrace(leaf_sigs)
        self._sigs[key] = list(leaf_sigs)
        rec.compiles += 1
        rec.compile_seconds += seconds
        rec.cost = cost
        sig_str = _fmt_sig(leaf_sigs)
        if len(key) > 2 and key[2]:
            sig_str += f" static={key[2]}"
        rec.signatures[key] = {"signature": sig_str, "seconds": seconds,
                               **(cost or {})}
        mdefs.XLA_COMPILES.inc(tags=tags)
        mdefs.XLA_COMPILE_SECONDS.observe(seconds, tags=tags)
        if cost:
            if cost.get("flops"):
                mdefs.XLA_PROGRAM_FLOPS.set(cost["flops"], tags=tags)
            if cost.get("bytes_accessed"):
                mdefs.XLA_PROGRAM_BYTES.set(cost["bytes_accessed"],
                                            tags=tags)
        if retrace_from is not None:
            rec.retraces += 1
            mdefs.XLA_RETRACES.inc(tags=tags)
            logger.warning(
                "xla retrace: %s recompiled for a new signature "
                "(policy=%s)\n  was: %s\n  now: %s",
                self.name, self.shape_policy, _fmt_sig(retrace_from),
                sig_str)
        with _state_lock:
            node = (_node_id or "local")[:12]
        # ONE record per (program, process), overwritten with the latest
        # compile plus cumulative counters — a shape-churning program
        # must not grow the head KV by one key per retrace forever.
        _queue_kv(PROGRAM_KV_NS, f"{self.name}:{node}:{os.getpid()}",
                  {"program": self.name, "node_id": node,
                   "pid": os.getpid(), "signature": sig_str,
                   "compile_seconds": seconds,
                   "compiles": rec.compiles, "retraces": rec.retraces,
                   "retrace": retrace_from is not None,
                   "policy": self.shape_policy, "ts": time.time(),
                   **(cost or {})})
        _on_xla_activity()

    def _detect_retrace(self, leaf_sigs) -> Optional[List[Tuple]]:
        """Returns the closest prior signature when this compile is a
        retrace, else None. Must run before the new signature is
        recorded."""
        if self.shape_policy == "free" or not self._sigs:
            return None
        prior = list(self._sigs.values())
        if self.shape_policy == "static":
            return prior[-1]
        # bucketed: expected growth = every changed dim is a power of
        # two (or explicitly allowed, e.g. a non-pow2 max_len cap).
        best = prior[-1]
        for old in prior:
            dims = _changed_dims(old, leaf_sigs)
            if dims is None:
                continue
            if all(_is_pow2(d) or d in self.allowed_dims for d in dims):
                return None
            best = old
        return best

    # ------------------------------------------------------------- timing
    def _note_cadence(self) -> None:
        now = time.perf_counter()
        prev, self._last_call = self._last_call, now
        if prev is not None and not self._external_timing:
            dt = now - prev
            if dt > 0:
                _set_achieved(_record(self.name),
                              self._cost_for(self._last_key), dt)

    def note_execution(self, seconds: float,
                       bytes_hint: Optional[float] = None
                       ) -> Optional[Dict[str, float]]:
        """Feed back a MEASURED wall time for the most recent call (the
        serve tick measures dispatch→fetch, prefill measures
        dispatch→first-token sync). Disables the cadence fallback for
        this wrapper and returns the achieved figures.

        ``bytes_hint`` overrides the compiler cost-analysis bytes for
        the achieved-bandwidth gauge: programs whose real traffic is
        data-dependent (the paged decode tick reads only LIVE KV blocks)
        would otherwise be priced at the compiled worst case — the
        gauge must scale with live tokens, not ``S_max``."""
        self._external_timing = True
        if seconds <= 0:
            return None
        cost = self._cost_for(self._last_key)
        if bytes_hint is not None and bytes_hint > 0:
            cost = dict(cost) if cost else {}
            cost["bytes_accessed"] = float(bytes_hint)
        return _set_achieved(_record(self.name), cost, seconds)

    def _cost_for(self, key) -> Optional[Dict[str, Any]]:
        rec = _programs.get(self.name)
        if rec is None:
            return None
        if key is not None and key in rec.signatures:
            return rec.signatures[key]
        return rec.cost


def _harvest_cost(compiled) -> Optional[Dict[str, float]]:
    """FLOPs / bytes-accessed from the executable's compiler cost
    analysis (per-device figures; None when the backend offers none)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend without cost analysis
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for ours, theirs in (("flops", "flops"),
                         ("bytes_accessed", "bytes accessed")):
        v = ca.get(theirs)
        if v is not None and v == v:     # drop NaN
            out[ours] = float(v)
    return out or None


def _set_achieved(rec: _ProgramRecord, cost, seconds: float
                  ) -> Optional[Dict[str, float]]:
    if not cost:
        return None
    from ray_tpu._private import metrics_defs as mdefs

    tags = {"program": rec.name}
    out: Dict[str, float] = {}
    flops = cost.get("flops")
    nbytes = cost.get("bytes_accessed")
    if flops:
        out["achieved_flops_per_s"] = flops / seconds
        mdefs.XLA_ACHIEVED_FLOPS.set(out["achieved_flops_per_s"],
                                     tags=tags)
        peak = _device_peak_flops()
        if peak:
            out["model_flops_utilization"] = flops / seconds / peak
            mdefs.XLA_MFU.set(out["model_flops_utilization"], tags=tags)
    if nbytes:
        out["achieved_bandwidth_bytes_per_s"] = nbytes / seconds
        mdefs.XLA_ACHIEVED_BW.set(
            out["achieved_bandwidth_bytes_per_s"], tags=tags)
    return out or None


_peak_cache: List[Optional[float]] = []


def _device_peak_flops() -> Optional[float]:
    if not _peak_cache:
        peak = None
        try:
            import jax

            kind = getattr(jax.devices()[0], "device_kind", "")
            for name, flops in PEAK_FLOPS.items():
                if kind.startswith(name):
                    peak = flops
                    break
        except Exception:  # noqa: BLE001
            pass
        _peak_cache.append(peak)
    return _peak_cache[0]


def instrument(fn=None, *, name: Optional[str] = None,
               shape_policy: str = "static",
               allowed_dims: Sequence[int] = (), aot: bool = True,
               **jit_kwargs):
    """``jax.jit`` through the XLA monitor. Drop-in: all jit kwargs
    (``donate_argnums``, ``in_shardings``, ...) pass through."""
    if fn is None:
        return functools.partial(instrument, name=name,
                                 shape_policy=shape_policy,
                                 allowed_dims=allowed_dims, aot=aot,
                                 **jit_kwargs)
    return InstrumentedJit(fn, name or getattr(fn, "__name__", "jit_fn"),
                           shape_policy=shape_policy,
                           allowed_dims=allowed_dims, aot=aot,
                           **jit_kwargs)


# -------------------------------------------------------- device memory
def sample_device_memory(node_id: Optional[str] = None,
                         force: bool = False) -> List[Dict[str, Any]]:
    """Per-device ``memory_stats()`` vitals as tagged gauges.

    Never triggers a fresh jax import unless ``force`` — importing jax
    grabs the accelerator, and a supervisor process (the node agent on a
    TPU host) must not steal chips from its workers. CPU devices report
    no memory stats; that's the documented graceful None."""
    if not force and "jax" not in sys.modules:
        return []
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 - no backend at all
        return []
    from ray_tpu._private import metrics_defs as mdefs

    with _state_lock:
        node = (node_id or _node_id or "local")[:12]
    out = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        if not stats:
            continue
        tags = {"node_id": node, "device": f"{d.platform}:{d.id}"}
        entry: Dict[str, Any] = {"device": tags["device"],
                                 "kind": getattr(d, "device_kind", "?")}
        for field, gauge in (
                ("bytes_in_use", mdefs.DEVICE_MEM_USED),
                ("peak_bytes_in_use", mdefs.DEVICE_MEM_PEAK),
                ("bytes_limit", mdefs.DEVICE_MEM_LIMIT)):
            v = stats.get(field)
            if v is not None:
                gauge.set(float(v), tags=tags)
                entry[field] = int(v)
        out.append(entry)
    return out


# --------------------------------------------------------- capture plane
def request_capture(gcs_address: str, node: str = "*",
                    duration_s: float = 2.0,
                    capture_id: Optional[str] = None) -> str:
    """Publish an on-demand profiler capture command (CLI/dashboard
    entry point). Every XLA-active process on a matching node captures
    for ``duration_s`` and registers its trace dir under
    ``__profiles__/<capture_id>/...``."""
    import pickle

    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    if not capture_id:
        capture_id = f"cap-{int(time.time())}-{os.getpid() % 10000:04d}"
    gcs = rpc.get_stub("GcsService", gcs_address)
    gcs.Publish(pb.PublishRequest(
        channel=PROFILE_CHANNEL,
        data=pickle.dumps({"capture_id": capture_id, "node": node or "*",
                           "duration_s": float(duration_s),
                           "ts": time.time()})), timeout=10)
    return capture_id


def _kv_scan(gcs_address: str, ns: str) -> List[Dict[str, Any]]:
    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    gcs = rpc.get_stub("GcsService", gcs_address)
    out = []
    for key in gcs.KvKeys(pb.KvRequest(ns=ns, prefix="")).keys:
        reply = gcs.KvGet(pb.KvRequest(ns=ns, key=key))
        if not reply.found:
            continue
        try:
            out.append(json.loads(reply.value))
        except ValueError:
            continue
    return out


def list_captures(gcs_address: str) -> List[Dict[str, Any]]:
    """Registered captures, newest first."""
    out = _kv_scan(gcs_address, PROFILE_KV_NS)
    out.sort(key=lambda e: e.get("ts", 0), reverse=True)
    return out


def list_programs(gcs_address: str) -> List[Dict[str, Any]]:
    """The persisted cost-analysis program registry (CLI `ray-tpu
    profile programs` and the dashboard read through this)."""
    out = _kv_scan(gcs_address, PROGRAM_KV_NS)
    out.sort(key=lambda e: (e.get("program", ""), e.get("ts", 0)))
    return out


def start_profile_listener(gcs_address: str,
                           node_id: Optional[str] = None) -> None:
    """Explicitly start this process's capture listener (tests, embedded
    engines); production processes get it lazily via :func:`connect` +
    first compile."""
    connect(gcs_address, node_id=node_id)
    _ensure_listener(gcs_address)
    _ensure_maintenance()


def _ensure_listener(address: str) -> None:
    with _state_lock:
        if address in _listeners:
            return
        stop = _listeners[address] = threading.Event()
    threading.Thread(target=_listener_loop, args=(address, stop),
                     daemon=True, name="xla-profile-listener").start()


def _ensure_maintenance() -> None:
    global _maintenance_stop
    with _state_lock:
        if _maintenance_stop is not None:
            return
        stop = _maintenance_stop = threading.Event()
    threading.Thread(target=_maintenance_loop, args=(stop,), daemon=True,
                     name="xla-monitor-maintenance").start()


def _maintenance_loop(stop: threading.Event) -> None:
    from ray_tpu._private import metrics_pusher

    interval = metrics_pusher.push_interval_s()
    while not stop.wait(interval):
        try:
            _flush_pending_kv()
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
        try:
            sample_device_memory()
        except Exception:  # noqa: BLE001
            pass


def _listener_loop(address: str, stop: threading.Event) -> None:
    import pickle

    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    failures = 0
    while not stop.is_set() and failures < 10:
        try:
            gcs = rpc.get_stub("GcsService", address)
            stream = gcs.Subscribe(pb.SubscribeRequest(
                channels=[PROFILE_CHANNEL],
                subscriber_id=f"xla-{os.getpid()}"),
                timeout=365 * 86400.0)
            for msg in stream:
                failures = 0
                if stop.is_set():
                    break
                try:
                    cmd = pickle.loads(msg.data)
                except Exception:  # noqa: BLE001
                    continue
                if _matches_node(cmd.get("node", "*")):
                    threading.Thread(
                        target=_do_capture, args=(cmd, address),
                        daemon=True, name="xla-profile-capture").start()
        except Exception:  # noqa: BLE001 — cluster down or restarting
            failures += 1
            stop.wait(min(0.5 * failures, 5.0))
    with _state_lock:
        if _listeners.get(address) is stop:
            del _listeners[address]


def _matches_node(target: str) -> bool:
    if target in ("", "*", "all"):
        return True
    with _state_lock:
        node = _node_id
    return bool(node) and (node == target or node.startswith(target))


def _do_capture(cmd: Dict[str, Any], address: str) -> None:
    from ray_tpu._private import metrics_defs as mdefs
    from ray_tpu._private import rpc
    from ray_tpu.protobuf import ray_tpu_pb2 as pb

    capture_id = str(cmd.get("capture_id") or "cap-unnamed")
    duration = max(float(cmd.get("duration_s", 2.0)), 0.1)
    with _state_lock:
        node = (_node_id or "local")[:12]
    tag = f"{node}-{os.getpid()}"
    key = f"{capture_id}/{tag}"
    record: Dict[str, Any] = {
        "capture_id": capture_id, "node_id": node, "pid": os.getpid(),
        "duration_s": duration, "ts": time.time()}

    def register() -> None:
        try:
            gcs = rpc.get_stub("GcsService", address)
            gcs.KvPut(pb.KvRequest(ns=PROFILE_KV_NS, key=key,
                                   value=json.dumps(record).encode(),
                                   overwrite=True), timeout=10)
        except Exception:  # noqa: BLE001
            logger.exception("profile capture %s: registration failed",
                             capture_id)

    if not _capture_lock.acquire(blocking=False):
        # Registered under a DISTINCT key: a duplicate command must not
        # clobber the in-flight capture's record.
        key = f"{capture_id}/{tag}-busy"
        record.update(status="busy",
                      error="a capture is already in progress")
        register()
        return
    try:
        trace_dir = os.path.join(session_dir(), "profiles", capture_id,
                                 tag)
        os.makedirs(trace_dir, exist_ok=True)
        record.update(status="capturing", trace_dir=trace_dir)
        register()
        import jax

        jax.profiler.start_trace(trace_dir)
        try:
            time.sleep(duration)
        finally:
            jax.profiler.stop_trace()
        files = sum(len(fs) for _, _, fs in os.walk(trace_dir))
        record.update(status="done", files=files, end_ts=time.time())
        mdefs.PROFILE_CAPTURES.inc(tags={"status": "done"})
    except Exception as e:  # noqa: BLE001
        record.update(status="failed", error=repr(e), end_ts=time.time())
        mdefs.PROFILE_CAPTURES.inc(tags={"status": "failed"})
        logger.exception("profile capture %s failed", capture_id)
    finally:
        _capture_lock.release()
        register()
