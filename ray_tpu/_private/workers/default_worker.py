"""Worker process: executes tasks and hosts actors.

Reference: ``python/ray/_private/workers/default_worker.py`` + the executor
side of the CoreWorker (``core_worker.cc:3813`` HandlePushTask →
``ExecuteTask`` :3239 → language callback). This process:

* starts a ``WorkerService`` gRPC server and announces itself to the node
  manager (the raylet's worker-registration handshake);
* executes pushed normal tasks one at a time (the reference leases a worker
  to one owner at a time);
* hosts actor instances with per-caller sequence ordering (reference:
  ``actor_scheduling_queue.h`` — out-of-order arrivals wait for their
  sequence number);
* resolves top-level ``ObjectRef`` args through the cluster runtime before
  invoking user code (reference: ``dependency_resolver.h``), and returns
  results inline when small or via the node object store when large.
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import threading
import time
import traceback
from concurrent.futures import CancelledError as _FuturesCancelledError
from typing import Any, Dict, List, Optional

from ray_tpu import exceptions
from ray_tpu._private import pg_context
from ray_tpu._private import rpc
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime.cluster import (
    ClusterRuntime,
    INLINE_RESULT_MAX,
    dumps,
    loads,
    loads_payload,
    put_bytes_to_node,
)
from ray_tpu.protobuf import ray_tpu_pb2 as pb

logger = logging.getLogger(__name__)


class _LogTee:
    """Mirror a worker's stdout/stderr to the driver (reference: the log
    monitor tailing worker log files + ``log_to_driver`` printing with a
    ``(pid=...)`` prefix, ``_private/log_monitor.py``). Lines buffer
    briefly and ship over the GCS LOG pubsub channel; drivers subscribe
    and re-print them."""

    FLUSH_PERIOD_S = 0.1

    def __init__(self, orig, stream_name: str, publisher):
        self._orig = orig
        self._stream = stream_name
        self._publisher = publisher
        self._partial = ""

    def write(self, s):
        self._orig.write(s)
        self._partial += s
        *lines, self._partial = self._partial.split("\n")
        for line in lines:
            if line:
                self._publisher.add(self._stream, line)
        return len(s)

    def flush(self):
        self._orig.flush()

    def fileno(self):
        return self._orig.fileno()

    def isatty(self):
        return False


class _TaskEventReporter:
    """Batch task state transitions to the GCS task-event sink
    (reference C32: ``gcs_task_manager.h`` — workers buffer task events
    and flush them periodically to the GCS). The buffering/flush loop is
    the shared BufferedPublisher (one flush pattern for task events and
    tracing spans)."""

    def __init__(self, gcs, worker_id: str, node_id: str):
        from ray_tpu._private.events import BufferedPublisher

        self._worker_id = worker_id
        self._node_id = node_id
        self._pub = BufferedPublisher("TASK_EVENT", lambda: gcs, cap=2000)

    def report(self, task_id_hex: str, name: str, state: str,
               **extra) -> None:
        self._pub.add({
            "task_id": task_id_hex, "name": name, "state": state,
            "ts": time.time(), "worker_id": self._worker_id[:12],
            "node_id": self._node_id[:12], **extra})


class _LogPublisher:
    def __init__(self, gcs, worker_id: str, namespace: str = "default"):
        self._gcs = gcs
        self._worker_id = worker_id
        self._namespace = namespace
        self._pid = os.getpid()
        self._buf: List[tuple] = []
        self._lock = threading.Lock()
        threading.Thread(target=self._flush_loop, daemon=True,
                         name="log-pub").start()

    def add(self, stream: str, line: str) -> None:
        with self._lock:
            self._buf.append((stream, line))
            if len(self._buf) > 1000:  # chatty task: drop oldest
                del self._buf[:500]

    def _flush_loop(self):
        while True:
            time.sleep(_LogTee.FLUSH_PERIOD_S)
            with self._lock:
                buf, self._buf = self._buf, []
            if not buf:
                continue
            by_stream: Dict[str, List[str]] = {}
            for stream, line in buf:
                by_stream.setdefault(stream, []).append(line)
            for stream, lines in by_stream.items():
                try:
                    self._gcs.Publish(pb.PublishRequest(
                        channel="LOG",
                        data=pickle.dumps({"name": self._worker_id[:8],
                                           "pid": self._pid,
                                           "ns": self._namespace,
                                           "stream": stream,
                                           "lines": lines})))
                except Exception:  # noqa: BLE001 — logs are best-effort
                    pass


class _ActorRunner:
    """Execution modes for one hosted actor instance.

    * **ordered** (default, ``max_concurrency == 1``, no coroutine methods,
      no concurrency groups): per-caller sequence ordering + single-slot
      execution (reference: ``actor_scheduling_queue.h``).
    * **threaded** (``max_concurrency > 1`` or ``concurrency_groups``
      declared on a sync class): calls run concurrently on RPC threads
      gated by semaphores — one per concurrency group plus a default
      (reference: threaded actors, ``core_worker.cc`` BoundedExecutor +
      ``concurrency_group_manager.h``). Per-caller ordering is
      deliberately NOT enforced.
    * **async** (any ``async def`` method on the class): a dedicated
      asyncio event loop thread runs every call (reference: async actors,
      ``src/ray/core_worker/fiber.h`` — fibers there, one loop here
      because Python coroutines ARE the fiber). Calls *start* in
      per-caller submission order, then interleave at await points;
      ``max_concurrency`` (default 1000) caps concurrent awaits via
      asyncio semaphores, per concurrency group.

    Concurrency groups are declared at the class level
    (``@ray_tpu.remote(concurrency_groups={"io": 2})``) and picked per
    method with ``@ray_tpu.method(concurrency_group="io")`` — the group
    name travels with the pickled method attribute, so the worker reads
    it straight off the instance.
    """

    def __init__(self, instance: Any, max_concurrency: Optional[int] = None,
                 concurrency_groups: Optional[Dict[str, int]] = None):
        from ray_tpu._private import concurrency

        self.instance = instance
        self.cond = threading.Condition()
        self.next_seq: Dict[bytes, int] = {}
        self.dead = False
        self.pg_ctx: Optional[tuple] = None  # (group_id, bundle_idx, capture)
        self.is_async = concurrency.class_is_async(type(instance))
        mc = concurrency.effective_max_concurrency(self.is_async,
                                                   max_concurrency)
        self.max_concurrency = mc
        self.groups: Dict[str, int] = dict(concurrency_groups or {})
        self.ordered = (not self.is_async and mc == 1 and not self.groups)
        self.loop: Optional[Any] = None
        if self.is_async:
            import asyncio

            self.loop = asyncio.new_event_loop()
            self._async_sems: Dict[str, Any] = {}
            # task_id -> asyncio.Task, for ray_tpu.cancel() (reference:
            # async actor tasks are the cancellable kind).
            self.async_tasks: Dict[bytes, Any] = {}
            ready = threading.Event()

            def loop_body():
                asyncio.set_event_loop(self.loop)
                ready.set()
                self.loop.run_forever()

            threading.Thread(target=loop_body, daemon=True,
                             name="actor-async-loop").start()
            ready.wait(timeout=10.0)
        else:
            self.sem = threading.Semaphore(mc)
            self._thread_sems = {name: threading.Semaphore(int(cap))
                                 for name, cap in self.groups.items()}

    # -- concurrency-group resolution -----------------------------------
    def _group_of(self, method) -> str:
        from ray_tpu._private import concurrency

        return concurrency.group_of(method, self.groups)

    def thread_sem_for(self, method) -> threading.Semaphore:
        group = self._group_of(method)
        return self._thread_sems[group] if group else self.sem

    def async_sem_for(self, method):
        import asyncio

        group = self._group_of(method)
        sem = self._async_sems.get(group)
        if sem is None:
            cap = self.groups.get(group, self.max_concurrency)
            sem = self._async_sems[group] = asyncio.Semaphore(int(cap))
        return sem

    def stop_loop(self):
        if self.loop is not None:
            try:
                self.loop.call_soon_threadsafe(self.loop.stop)
            except Exception:  # noqa: BLE001 — already closed
                pass

    def wait_turn(self, caller: bytes, seq: int) -> bool:
        deadline = time.monotonic() + 120.0
        with self.cond:
            while not self.dead and self.next_seq.get(caller, 0) != seq:
                if self.next_seq.get(caller, 0) > seq:
                    return False  # duplicate/stale
                if time.monotonic() > deadline:
                    return False  # ordering gap (stale session) — fail task
                self.cond.wait(timeout=1.0)
            return not self.dead

    def complete(self, caller: bytes, seq: int):
        with self.cond:
            self.next_seq[caller] = max(self.next_seq.get(caller, 0), seq + 1)
            self.cond.notify_all()


class WorkerServer:
    def __init__(self, node_address: str, gcs_address: str, worker_id: str,
                 node_id: str):
        self.worker_id = worker_id
        self.node_id = node_id
        self.runtime = ClusterRuntime(gcs_address, node_address,
                                      is_worker=True, worker_id=worker_id,
                                      node_id=node_id)
        worker_mod._global_worker = worker_mod.Worker(self.runtime, "worker")
        self._actors: Dict[bytes, _ActorRunner] = {}
        self._task_lock = threading.Lock()  # one normal task at a time
        # Cancellation state (reference: CancelTaskOnExecutor,
        # core_worker.h:1655): running normal tasks by id -> executing
        # thread; cancels that arrive before their PushTask are remembered
        # briefly so the push fails fast instead of racing.
        self._cancel_lock = threading.Lock()
        self._running: Dict[bytes, dict] = {}
        self._precancelled: Dict[bytes, float] = {}
        self._exit = threading.Event()
        # Pool must exceed any single submitter's concurrency: ordered
        # actor pushes BLOCK a server thread until their sequence number's
        # turn, so a pool smaller than the in-flight push count can starve
        # the very push holding the next sequence number (deadlock until
        # the ordering-gap timeout). Paired with the submitter-side
        # per-actor send window (cluster.py ACTOR_SEND_WINDOW).
        self._server, self.port = rpc.serve("WorkerService", self,
                                            max_workers=128)
        self.address = f"127.0.0.1:{self.port}"
        # Fastpath task plane: the latency-critical PushTask traffic rides
        # framed TCP (fastpath.py) instead of per-call gRPC; gRPC stays as
        # the fallback and for the rare control RPCs.
        from ray_tpu._private import fastpath

        self._fast = fastpath.FastServer(self._fast_handler)
        self.fast_address = self._fast.address
        self.node = rpc.get_stub("NodeService", node_address)
        if os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0":
            import sys

            pub = _LogPublisher(self.runtime.gcs, worker_id,
                                namespace=self.runtime.namespace)
            sys.stdout = _LogTee(sys.stdout, "stdout", pub)
            sys.stderr = _LogTee(sys.stderr, "stderr", pub)
        self.task_events: Optional[_TaskEventReporter] = None
        if os.environ.get("RAY_TPU_TASK_EVENTS", "1") != "0":
            self.task_events = _TaskEventReporter(self.runtime.gcs,
                                                  worker_id, node_id)
        self.node.AnnounceWorker(pb.AnnounceWorkerRequest(
            worker_id=worker_id, address=self.address, pid=os.getpid(),
            fast_address=self.fast_address))

    def _fast_handler(self, kind: int, payload: bytes) -> bytes:
        from ray_tpu._private import fastpath

        if kind == fastpath.KIND_PUSH_TASK:
            req = pb.PushTaskRequest()
            req.ParseFromString(payload)
            return self.PushTask(req, None).SerializeToString()
        if kind == fastpath.KIND_PUSH_BATCH:
            breq = pb.PushTaskBatchRequest()
            breq.ParseFromString(payload)
            return self.PushTaskBatch(breq, None).SerializeToString()
        raise ValueError(f"unknown fastpath frame kind {kind}")

    # ------------------------------------------------------------- helpers
    def _payload_bytes(self, spec) -> bytes:
        """Inline payload, or fetch a promoted one from the object store
        (reference: plasma-promoted task args, core_worker.cc:1527)."""
        if spec.payload_ref:
            raw = self.runtime.fetch_object_bytes(bytes(spec.payload_ref))
            if raw is None:
                raise exceptions.RayTpuError(
                    f"task payload object "
                    f"{bytes(spec.payload_ref).hex()[:16]} was lost")
            return raw
        return spec.payload

    def _stream_generator(self, gen, spec) -> int:
        """Drain a streaming-generator task via the shared protocol helper
        (reference: ObjectRefStream, task_manager.h:104). Each yielded value
        becomes its own store object, visible to the caller's
        ObjectRefGenerator before the task finishes; returns the item count,
        which rides the declared return."""
        import inspect

        from ray_tpu._private.object_ref import drain_stream

        if inspect.isasyncgen(gen):
            # async-generator streaming task outside an async actor: drain
            # on a private loop.
            import asyncio

            return asyncio.run(self._drain_stream_async(gen, spec))
        if not (inspect.isgenerator(gen) or hasattr(gen, "__next__")):
            raise TypeError(
                f"num_returns='streaming' requires a generator "
                f"{'method' if spec.actor_id else 'function'}, but "
                f"{spec.name!r} returned {type(gen).__name__}")
        def store_item(oid, item):
            if not put_bytes_to_node(self.node, oid.binary(), dumps(item),
                                     self.worker_id):
                # A stream item MUST live in the store (the consumer
                # fetches it by id); rejection fails the task rather than
                # silently dropping items mid-stream.
                raise exceptions.RayTpuError(
                    f"object store rejected stream item {oid.hex()[:12]} "
                    f"(store full even after spilling)")

        return drain_stream(gen, TaskID(bytes(spec.task_id)), store_item)

    def _resolve_args(self, args, kwargs):
        """Top-level ObjectRef resolution (nested refs pass through)."""
        refs = [a for a in args if isinstance(a, ObjectRef)]
        refs += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
        if refs:
            values = self.runtime.get(refs, timeout=300.0)
            table = {r.id(): v for r, v in zip(refs, values)}
            args = tuple(table[a.id()] if isinstance(a, ObjectRef) else a
                         for a in args)
            kwargs = {k: (table[v.id()] if isinstance(v, ObjectRef) else v)
                      for k, v in kwargs.items()}
        return args, kwargs

    def _package_results(self, result, return_ids) -> pb.PushTaskResult:
        n = len(return_ids)
        if n == 1:
            values = [result]
        elif isinstance(result, (tuple, list)) and len(result) == n:
            values = list(result)
        else:
            err = exceptions.RayTpuError(
                f"Task declared num_returns={n} but returned "
                f"{type(result).__name__}")
            return pb.PushTaskResult(ok=False, error=pickle.dumps(err))
        out = pb.PushTaskResult(ok=True)
        for oid, value in zip(return_ids, values):
            data = dumps(value)
            if len(data) <= INLINE_RESULT_MAX or not put_bytes_to_node(
                    self.node, bytes(oid), data, self.worker_id):
                # Small result — or the store REJECTED a large one (full
                # even after spilling): degrade to inline so the result
                # still reaches the owner (whose flusher re-seats it in
                # the store once pressure clears) instead of vanishing.
                out.inline_results.append(data)
                out.in_store.append(False)
            else:
                out.inline_results.append(b"")
                out.in_store.append(True)
        return out

    def _error_result(self, e: BaseException, name: str) -> pb.PushTaskResult:
        if isinstance(e, exceptions.RayTpuError):
            err: BaseException = e
        else:
            err = exceptions.RayTaskError.from_exception(e, name)
        try:
            blob = pickle.dumps(err)
        except Exception:  # unpicklable exception chain — degrade to text
            err = exceptions.RayTaskError(
                name, "".join(traceback.format_exception(e)))
            blob = pickle.dumps(err)
        return pb.PushTaskResult(ok=False, error=blob)

    # ------------------------------------------------------------- service
    def PushTask(self, request, context):
        spec = request.spec
        if spec.actor_id:
            return self._push_actor_task(spec)
        return self._push_normal_task(spec)

    def PushTaskBatch(self, request, context):
        """Execute a chunk of normal tasks back-to-back (one frame, one
        reply): lease-holding submitters drain their queues in batches so
        sub-millisecond tasks don't pay a full RPC round per task."""
        reply = pb.PushTaskBatchReply()
        for spec in request.specs:
            reply.results.append(self._push_normal_task(spec))
        return reply

    def _report_task(self, spec, state: str, **extra) -> None:
        if self.task_events is not None:
            self.task_events.report(bytes(spec.task_id).hex()[:16],
                                    spec.name, state, **extra)

    def _prune_precancelled(self) -> None:
        """Drop stale early-cancel records (caller holds _cancel_lock)."""
        if len(self._precancelled) > 32:
            cutoff = time.monotonic() - 60.0
            for k, ts in list(self._precancelled.items()):
                if ts < cutoff:
                    del self._precancelled[k]

    def _push_normal_task(self, spec) -> pb.PushTaskResult:
        tid = bytes(spec.task_id)
        with self._task_lock:
            with self._cancel_lock:
                self._prune_precancelled()
                if spec.cancelled or \
                        self._precancelled.pop(tid, None) is not None:
                    return self._error_result(
                        exceptions.TaskCancelledError(TaskID(tid)), spec.name)
                # in_user gates the async-exc: CancelTask only raises into
                # this thread while it executes USER code — never during
                # cleanup/packaging, where a stray exception would corrupt
                # worker state for the next task.
                entry = {"thread": threading.get_ident(),
                         "in_user": False, "cancelled": False}
                self._running[tid] = entry
            renv_restore = None
            ctx_token = None
            self._report_task(spec, "RUNNING")
            try:
                if spec.tpu_chips:
                    os.environ["TPU_VISIBLE_CHIPS"] = ",".join(
                        map(str, spec.tpu_chips))
                if spec.runtime_env:
                    from ray_tpu._private import runtime_env as renv_mod

                    renv_restore = renv_mod.apply(
                        pickle.loads(spec.runtime_env), self.runtime.gcs)
                (fn, args, kwargs), n_borrows = \
                    loads_payload(self._payload_bytes(spec))
                from ray_tpu._private import fn_ref as fn_ref_mod

                fn = fn_ref_mod.resolve(fn)
                if n_borrows:
                    # Flush the borrow (+1) registrations synchronously so
                    # the GCS observes them before the submitter's pin
                    # release (sent only after this push returns) — the
                    # ordering that makes the zero-dip race impossible.
                    self.runtime.refs.flush()
                args, kwargs = self._resolve_args(args, kwargs)
                if spec.placement_group_id:
                    # Children of a capturing task inherit its group
                    # (placement_group_capture_child_tasks semantics).
                    pg_context.set(bytes(spec.placement_group_id),
                                   spec.pg_bundle_index,
                                   spec.pg_capture_child_tasks)
                from ray_tpu.util import tracing
                from ray_tpu._private.runtime.local import _TaskCtx, _context

                # Task context: get_runtime_context() works on cluster
                # workers, and children submitted by this task are recorded
                # under it for recursive cancellation.
                ctx_token = _context.set(
                    _TaskCtx(TaskID(tid), name=spec.name))

                # The span covers generator DRAIN too: a streaming task's
                # real work happens consuming the generator, and children
                # submitted from its body must inherit the trace context
                # (and the capturing placement group).
                with tracing.execute_span(spec):
                    with self._cancel_lock:
                        if entry["cancelled"]:
                            raise exceptions.TaskCancelledError(TaskID(tid))
                        entry["in_user"] = True
                    try:
                        result = fn(*args, **kwargs)
                        import inspect as _inspect

                        if _inspect.iscoroutine(result):
                            # async def task: run to completion on a
                            # private loop (reference: async remote
                            # functions, async_compat.py).
                            import asyncio as _asyncio

                            result = _asyncio.run(result)
                        if spec.returns_stream:
                            result = self._stream_generator(result, spec)
                        elif hasattr(result, "__next__"):  # legacy gens
                            result = tuple(result) \
                                if len(spec.return_ids) > 1 else list(result)
                    finally:
                        with self._cancel_lock:
                            entry["in_user"] = False
                        if spec.placement_group_id:
                            pg_context.clear()
                out = self._package_results(result, spec.return_ids)
                self._report_task(spec, "FINISHED")
                return out
            except BaseException as e:  # noqa: BLE001
                self._report_task(spec, "FAILED", error=repr(e)[:200])
                return self._error_result(e, spec.name)
            finally:
                with self._cancel_lock:
                    self._running.pop(tid, None)
                self.runtime.drop_children(tid)
                if spec.placement_group_id:
                    # Idempotent re-clear: a cancel async-exc landing in
                    # the inner finally can abort its pg clear.
                    pg_context.clear()
                if ctx_token is not None:
                    from ray_tpu._private.runtime.local import _context

                    _context.reset(ctx_token)
                if renv_restore is not None:
                    # Reused worker: don't leak this task's cwd/env/path.
                    renv_restore()

    def _push_actor_task(self, spec) -> pb.PushTaskResult:
        runner = self._actors.get(spec.actor_id)
        if runner is None or runner.dead:
            err = exceptions.ActorDiedError(
                ActorID(bytes(spec.actor_id)), "actor not hosted here")
            return pb.PushTaskResult(ok=False, error=pickle.dumps(err))
        if runner.is_async:
            return self._push_async_actor_task(runner, spec)
        caller = bytes(spec.caller_address)
        ordered = runner.ordered
        sem: Optional[threading.Semaphore] = None
        if ordered:
            if not runner.wait_turn(caller, spec.sequence_no):
                err = exceptions.ActorDiedError(
                    ActorID(bytes(spec.actor_id)), "actor died")
                return pb.PushTaskResult(ok=False, error=pickle.dumps(err))
        else:
            try:
                sem = runner.thread_sem_for(
                    getattr(runner.instance, spec.method_name, None))
            except ValueError as e:  # unknown concurrency group
                return self._error_result(e, spec.method_name)
            sem.acquire()
            if runner.dead:
                sem.release()
                err = exceptions.ActorDiedError(
                    ActorID(bytes(spec.actor_id)), "actor died")
                return pb.PushTaskResult(ok=False, error=pickle.dumps(err))
        try:
            with self._cancel_lock:
                # After wait_turn/sem (the finally advances the sequence /
                # releases the slot): a cancelled actor task must not
                # leave a per-caller ordering hole.
                if spec.cancelled or \
                        self._precancelled.pop(
                            bytes(spec.task_id), None) is not None:
                    raise exceptions.TaskCancelledError(
                        TaskID(bytes(spec.task_id)))
            self._report_task(spec, "RUNNING",
                              actor_id=bytes(spec.actor_id).hex()[:12])
            (_, args, kwargs), n_borrows = \
                loads_payload(self._payload_bytes(spec))
            if n_borrows:
                self.runtime.refs.flush()  # borrow-before-pin-release order
            args, kwargs = self._resolve_args(args, kwargs)
            if spec.method_name == "__ray_dag_loop__":
                # Compiled-DAG pinned loop: the actor executes its channel
                # schedule until teardown (reference: aDAG ExecutableTask
                # loop); this call occupies the actor by design.
                from ray_tpu.experimental.channel import run_dag_loop

                result = run_dag_loop(runner.instance, *args)
                return self._package_results(result, spec.return_ids)
            method = getattr(runner.instance, spec.method_name)
            if runner.pg_ctx is not None:
                pg_context.set(*runner.pg_ctx)
            from ray_tpu.util import tracing

            with tracing.execute_span(spec, kind="actor_task"):
                try:
                    result = method(*args, **kwargs)
                finally:
                    if runner.pg_ctx is not None:
                        pg_context.clear()
                if spec.returns_stream:
                    result = self._stream_generator(result, spec)
            out = self._package_results(result, spec.return_ids)
            self._report_task(spec, "FINISHED")
            return out
        except exceptions.AsyncioActorExit:
            self._terminate_actor(spec.actor_id, "exit_actor() called")
            self._report_task(spec, "FINISHED")
            return self._package_results(None, spec.return_ids)
        except BaseException as e:  # noqa: BLE001
            self._report_task(spec, "FAILED", error=repr(e)[:200])
            return self._error_result(e, f"{spec.method_name}")
        finally:
            if ordered:
                runner.complete(caller, spec.sequence_no)
            else:
                sem.release()

    def _push_async_actor_task(self, runner: _ActorRunner,
                               spec) -> pb.PushTaskResult:
        """Async-actor execution (reference: ``core_worker/fiber.h`` +
        async actor event loop, ``python/ray/_private/async_compat.py``).

        The RPC thread admits the call in per-caller *submission* order
        (sequence turn), schedules a coroutine on the actor's dedicated
        event loop, releases the sequence immediately — so later calls
        from the same caller start while this one awaits — and then
        blocks for the result (the push reply carries it). Concurrency is
        capped by per-group asyncio semaphores inside the coroutine.
        """
        import asyncio

        caller = bytes(spec.caller_address)
        tid = bytes(spec.task_id)
        fut = None
        try:
            try:
                if not runner.wait_turn(caller, spec.sequence_no):
                    err = exceptions.ActorDiedError(
                        ActorID(bytes(spec.actor_id)), "actor died")
                    return pb.PushTaskResult(ok=False,
                                             error=pickle.dumps(err))
                # Cancellation checks live AFTER wait_turn and inside the
                # complete() finally: a cancelled task must still advance
                # the caller's sequence or later tasks wedge.
                with self._cancel_lock:
                    if spec.cancelled or \
                            self._precancelled.pop(tid, None) is not None:
                        raise exceptions.TaskCancelledError(TaskID(tid))
                self._report_task(spec, "RUNNING",
                                  actor_id=bytes(spec.actor_id).hex()[:12])
                (_, args, kwargs), n_borrows = \
                    loads_payload(self._payload_bytes(spec))
                if n_borrows:
                    self.runtime.refs.flush()  # borrow-before-pin-release
                args, kwargs = self._resolve_args(args, kwargs)
                with self._cancel_lock:
                    # Re-check: a cancel that arrived during the (possibly
                    # long) argument fetch would otherwise be lost.
                    if self._precancelled.pop(tid, None) is not None:
                        raise exceptions.TaskCancelledError(TaskID(tid))
                fut = asyncio.run_coroutine_threadsafe(
                    self._run_async_actor_method(runner, spec, args, kwargs),
                    runner.loop)
            finally:
                # Sequence completes at SCHEDULE time, not completion —
                # in-order starts, interleaved execution.
                runner.complete(caller, spec.sequence_no)
            result = fut.result()
            out = self._package_results(result, spec.return_ids)
            self._report_task(spec, "FINISHED")
            return out
        except exceptions.AsyncioActorExit:
            self._terminate_actor(spec.actor_id, "exit_actor() called")
            self._report_task(spec, "FINISHED")
            return self._package_results(None, spec.return_ids)
        except (asyncio.CancelledError, _FuturesCancelledError):
            # ray_tpu.cancel() cancelled the coroutine mid-await (the
            # thread-safe future re-raises it as the concurrent.futures
            # flavor).
            self._report_task(spec, "FAILED", error="cancelled")
            return self._error_result(
                exceptions.TaskCancelledError(TaskID(tid)), spec.method_name)
        except BaseException as e:  # noqa: BLE001
            self._report_task(spec, "FAILED", error=repr(e)[:200])
            return self._error_result(e, f"{spec.method_name}")

    async def _run_async_actor_method(self, runner: _ActorRunner, spec,
                                      args, kwargs):
        import asyncio

        tid = bytes(spec.task_id)
        with self._cancel_lock:
            # Last gap: a cancel between the schedule-time check and this
            # task actually starting lands in _precancelled.
            if self._precancelled.pop(tid, None) is not None:
                raise exceptions.TaskCancelledError(TaskID(tid))
        runner.async_tasks[tid] = asyncio.current_task()
        try:
            return await self._run_async_actor_body(runner, spec, args,
                                                    kwargs)
        finally:
            runner.async_tasks.pop(tid, None)

    async def _run_async_actor_body(self, runner: _ActorRunner, spec,
                                    args, kwargs):
        import inspect

        from ray_tpu.util import tracing

        method = getattr(runner.instance, spec.method_name)
        sem = runner.async_sem_for(method)
        async with sem:
            if runner.dead:
                raise exceptions.ActorDiedError(
                    ActorID(bytes(spec.actor_id)), "actor died")
            # pg_context is a ContextVar: each asyncio task carries its own
            # copy, so concurrent coroutines don't race on set/clear.
            if runner.pg_ctx is not None:
                pg_context.set(*runner.pg_ctx)
            try:
                with tracing.execute_span(spec, kind="actor_task"):
                    result = method(*args, **kwargs)
                    if inspect.iscoroutine(result):
                        result = await result
                    if spec.returns_stream:
                        if inspect.isasyncgen(result):
                            result = await self._drain_stream_async(result,
                                                                    spec)
                        else:
                            result = self._stream_generator(result, spec)
                    elif inspect.isasyncgen(result):
                        result = [item async for item in result]
                return result
            finally:
                if runner.pg_ctx is not None:
                    pg_context.clear()

    async def _drain_stream_async(self, agen, spec) -> int:
        """Async-generator streaming drain. Each item's store put is a
        blocking node RPC executed inline on the loop (sub-ms locally);
        matches the reference, where sync work inside an async actor
        blocks its loop."""
        from ray_tpu._private.object_ref import drain_stream_async

        def store_item(oid, item):
            if not put_bytes_to_node(self.node, oid.binary(), dumps(item),
                                     self.worker_id):
                raise exceptions.RayTpuError(
                    f"object store rejected stream item {oid.hex()[:12]} "
                    f"(store full even after spilling)")

        return await drain_stream_async(agen, TaskID(bytes(spec.task_id)),
                                        store_item)

    def CreateActor(self, request, context):
        info = request.info
        try:
            for k, v in request.env.items():
                os.environ[k] = v
            outer = pickle.loads(info.spec)
            if outer.get("runtime_env"):
                from ray_tpu._private import runtime_env as renv_mod

                renv_mod.apply(outer["runtime_env"], self.runtime.gcs)
            (cls, args, kwargs, options), n_borrows = \
                loads_payload(outer["payload"])
            if n_borrows:
                self.runtime.refs.flush()  # borrow-before-pin-release order
            pg_ctx = None
            if outer.get("pg") is not None:
                gid, idx = outer["pg"]
                pg_ctx = (gid, idx, bool(outer.get("pg_capture")))
            if pg_ctx is not None:
                pg_context.set(*pg_ctx)
            try:
                instance = cls(*args, **kwargs)
            finally:
                if pg_ctx is not None:
                    pg_context.clear()
            runner = _ActorRunner(
                instance,
                max_concurrency=getattr(options, "max_concurrency", None),
                concurrency_groups=getattr(options, "concurrency_groups",
                                           None))
            runner.pg_ctx = pg_ctx
            self._actors[bytes(info.actor_id)] = runner
            return pb.CreateActorReply(ok=True)
        except BaseException as e:  # noqa: BLE001
            return pb.CreateActorReply(
                ok=False,
                error="".join(traceback.format_exception(e)))

    def KillActor(self, request, context):
        self._terminate_actor(request.actor_id, "killed")
        return pb.Empty()

    def CancelTask(self, request, context):
        """Executor-side cancel (reference: ``CancelTaskOnExecutor``,
        ``core_worker.h:1655``).

        * running normal task → ``TaskCancelledError`` raised INTO the
          executing thread (async-exc; takes effect at the next Python
          bytecode — C-blocking calls finish first, same limitation as
          the reference's interrupt path);
        * running async-actor task → ``asyncio.Task.cancel()``;
        * not here yet → remembered briefly so a racing PushTask fails
          fast;
        * ``force`` → the worker process exits (the owner observes the
          death and stores the cancel error instead of retrying);
        * ``recursive`` → this runtime also cancels every child the task
          submitted.
        """
        tid = bytes(request.task_id)
        found = False
        with self._cancel_lock:
            info = self._running.get(tid)
            if info is not None:
                found = True
                if request.force:
                    pass  # handled below: the whole worker dies
                elif info.get("in_user"):
                    import ctypes

                    ctypes.pythonapi.PyThreadState_SetAsyncExc(
                        ctypes.c_ulong(info["thread"]),
                        ctypes.py_object(exceptions.TaskCancelledError))
                else:
                    # Not in user code yet (arg fetch / setup): flag it —
                    # the pre-execution check raises before fn() runs. If
                    # the task is already past user code, the result
                    # stands (cancel raced completion).
                    info["cancelled"] = True
            else:
                for runner in self._actors.values():
                    task = getattr(runner, "async_tasks", {}).get(tid) \
                        if runner.is_async else None
                    if task is not None:
                        found = True
                        runner.loop.call_soon_threadsafe(task.cancel)
                        break
                else:
                    self._prune_precancelled()
                    self._precancelled[tid] = time.monotonic()
        if request.recursive:
            self.runtime.cancel_children(tid, request.force)
        if request.force and found:
            # Reply first, then die: the owner's push fails with a
            # connection error and the cancel flag suppresses the retry.
            threading.Thread(target=self._delayed_exit, daemon=True).start()
        return pb.CancelTaskReply(found=found)

    def _terminate_actor(self, actor_id: bytes, reason: str):
        runner = self._actors.pop(bytes(actor_id), None)
        if runner is not None:
            runner.dead = True
            with runner.cond:
                runner.cond.notify_all()
            runner.stop_loop()
        # An actor worker is dedicated; exit so the pool reaps it.
        threading.Thread(target=self._delayed_exit, daemon=True).start()

    def _delayed_exit(self):
        time.sleep(0.2)
        os._exit(0)

    def Stacktrace(self, request, context):
        import faulthandler
        import io

        buf = io.StringIO()
        faulthandler.dump_traceback(file=buf)
        return pb.WorkerStacktraceReply(stacktrace=buf.getvalue())

    def run_forever(self):
        """Serve until exit; a worker whose node manager dies exits too
        (reference: workers die with their raylet)."""
        misses = 0
        try:
            while not self._exit.is_set():
                time.sleep(2)
                try:
                    self.node.GetObject(
                        pb.GetObjectRequest(object_id=b"\x00" * 28), timeout=2)
                    misses = 0
                except Exception:  # noqa: BLE001
                    misses += 1
                    if misses >= 3:
                        logger.warning("node manager unreachable; exiting")
                        os._exit(0)
        except KeyboardInterrupt:
            pass


def main():  # pragma: no cover - runs as a subprocess
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--node-id", required=True)
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {args.worker_id[:8]}] %(message)s")
    server = WorkerServer(args.node_address, args.gcs_address,
                          args.worker_id, args.node_id)
    server.run_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
