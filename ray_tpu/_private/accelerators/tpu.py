"""TPU accelerator manager: detection, visibility, and slice topology labels.

Re-design of the reference TPU accelerator support (reference:
``python/ray/_private/accelerators/tpu.py:70`` — ``TPUAcceleratorManager``:
GCE metadata/env detection :47-118, ``TPU`` + per-pod ``TPU-<type>-head``
resources :330, ``TPU_VISIBLE_CHIPS`` :154, pod-type → accelerator-type
mapping :307). Here TPU chips are *the* first-class accelerator: the
scheduler accounts individual chips, and slice topology (ICI neighborhoods)
is exposed as ``TPU-slice:<name>`` resources so placement groups can request
ICI-connected chips.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
NUM_CHIPS_OVERRIDE_ENV = "RAY_TPU_NUM_CHIPS"
ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # e.g. "v5litepod-256"
WORKER_ID_ENV = "TPU_WORKER_ID"

# chips per host for known generations (host = TPU VM).
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5litepod": 8, "v5p": 4, "v6e": 8}


class TPUAcceleratorManager:
    """Static helpers; mirrors the reference AcceleratorManager ABC surface
    (``_private/accelerators/accelerator.py:5``)."""

    resource_name = "TPU"

    @staticmethod
    def detect_num_chips() -> int:
        """Number of TPU chips visible to this host, without importing jax
        unless it is already loaded."""
        override = os.environ.get(NUM_CHIPS_OVERRIDE_ENV)
        if override is not None:
            return int(override)
        visible = os.environ.get(VISIBLE_CHIPS_ENV)
        if visible:
            return len([c for c in visible.split(",") if c != ""])
        if "jax" in sys.modules:
            try:
                jax = sys.modules["jax"]
                return len([d for d in jax.devices() if d.platform != "cpu"])
            except Exception:
                pass
        acc_type = os.environ.get(ACCELERATOR_TYPE_ENV)
        if acc_type:
            gen = acc_type.split("-")[0]
            return _CHIPS_PER_HOST.get(gen, 4)
        return 0

    @staticmethod
    def accelerator_type() -> Optional[str]:
        return os.environ.get(ACCELERATOR_TYPE_ENV)

    @staticmethod
    def pod_name() -> Optional[str]:
        """Logical slice/pod name this host belongs to (for TPU-<pod>-head)."""
        return os.environ.get("TPU_NAME") or os.environ.get("TPU_POD_NAME")

    @staticmethod
    def worker_id() -> int:
        return int(os.environ.get(WORKER_ID_ENV, "0"))

    @staticmethod
    def set_visible_chips(chip_ids: List[int]) -> None:
        """Restrict this process (and its jax) to the given chips — the analog
        of CUDA_VISIBLE_DEVICES sharing in the reference
        (``worker.py:991``, ``backend_executor.py:278``)."""
        os.environ[VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in chip_ids)
        # jax reads TPU_VISIBLE_CHIPS via libtpu at first init.

    @staticmethod
    def node_resources() -> Dict[str, float]:
        """Resources this host contributes to the cluster."""
        n = TPUAcceleratorManager.detect_num_chips()
        if n == 0:
            return {}
        res: Dict[str, float] = {"TPU": float(n)}
        acc = TPUAcceleratorManager.accelerator_type()
        if acc:
            res[f"accelerator_type:{acc}"] = 1.0
            # The host with worker id 0 of a slice carries the slice-head
            # resource so exactly one actor per slice can claim coordination
            # (reference: TPU-<pod>-head resource, tpu.py:330).
            if TPUAcceleratorManager.worker_id() == 0:
                res[f"TPU-{acc}-head"] = 1.0
        pod = TPUAcceleratorManager.pod_name()
        if pod:
            res[f"TPU-slice:{pod}"] = float(n)
        return res
