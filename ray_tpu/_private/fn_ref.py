"""Cached function serialization for the task hot path.

Reference rationale: the reference exports a remote function ONCE to the
GCS function table and submits tasks carrying only its function id
(``python/ray/_private/function_manager.py`` export/fetch). Re-running
cloudpickle's reduction graph walk per submitted task — and the matching
``cloudpickle.loads`` per executed task — costs ~100 us each, a large
fraction of a sub-millisecond task budget. :class:`FnRef` is the redesign:
the decorated function is pickled once on the driver, travels as an opaque
blob keyed by digest, and each worker unpickles it once and caches the
result by digest.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict


class FnRef:
    """A pre-pickled callable. The blob is embedded in the task payload;
    executors resolve it through a per-process digest cache."""

    __slots__ = ("blob", "digest")

    def __init__(self, blob: bytes, digest: bytes):
        self.blob = blob
        self.digest = digest

    def __reduce__(self):
        return (FnRef, (self.blob, self.digest))

    @staticmethod
    def of(fn: Callable):
        """Pickle ``fn`` once, or return None when its closure captures
        ObjectRefs — those need the per-submit Serializer pass so each
        task pins the contained refs for its flight time (a pre-pickled
        blob would skip pinning and let the refs be freed mid-flight)."""
        from ray_tpu._private.serialization import Serializer

        s = Serializer().serialize(fn)
        if s.contained_refs:
            return None
        blob = s.to_bytes()
        return FnRef(blob, hashlib.sha1(blob).digest())


_cache: Dict[bytes, Any] = {}
_cache_lock = threading.Lock()
_CACHE_CAP = 1024


def resolve(fn: Any) -> Any:
    """Return the callable behind ``fn`` (identity for plain callables)."""
    if not isinstance(fn, FnRef):
        return fn
    with _cache_lock:
        cached = _cache.get(fn.digest)
    if cached is not None:
        return cached
    from ray_tpu._private.serialization import SerializedObject, Serializer

    loaded = Serializer().deserialize(SerializedObject.parse(fn.blob))
    with _cache_lock:
        while len(_cache) >= _CACHE_CAP:
            _cache.pop(next(iter(_cache)))
        _cache[fn.digest] = loaded
    return loaded
