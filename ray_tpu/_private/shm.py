"""ctypes binding to the native shared-memory object store (native/shm_store.cpp).

Two roles (mirroring plasma store vs plasma client, reference C12):

* :class:`ShmStore` — lives in the node manager; owns the index, LRU
  eviction, and segment lifecycle.
* :class:`ShmClient` — lives in workers/drivers; creates sealed segments
  directly (zero-copy put: data never crosses a socket) and maps segments
  read-only for get.
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import threading
import uuid
from typing import Optional, Tuple

from ray_tpu._private.native_build import native_lib_path

logger = logging.getLogger(__name__)

_NAME_CAP = 192


def _load() -> Optional[ctypes.CDLL]:
    path = native_lib_path("shm_store")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.shm_store_create.restype = ctypes.c_void_p
    lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_store_destroy.argtypes = [ctypes.c_void_p]
    lib.shm_store_put.restype = ctypes.c_int
    lib.shm_store_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_store_register.restype = ctypes.c_int
    lib.shm_store_register.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_store_get.restype = ctypes.c_int
    lib.shm_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.shm_store_contains.restype = ctypes.c_int
    lib.shm_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_coldest.restype = ctypes.c_int
    lib.shm_store_coldest.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64]
    lib.shm_store_delete.restype = ctypes.c_int
    lib.shm_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_used.restype = ctypes.c_uint64
    lib.shm_store_used.argtypes = [ctypes.c_void_p]
    lib.shm_store_count.restype = ctypes.c_uint64
    lib.shm_store_count.argtypes = [ctypes.c_void_p]
    lib.shm_client_map.restype = ctypes.c_void_p
    lib.shm_client_map.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_client_unmap.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.shm_client_create.restype = ctypes.c_int
    lib.shm_client_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_uint64]
    lib.shm_client_unlink.restype = ctypes.c_int
    lib.shm_client_unlink.argtypes = [ctypes.c_char_p]
    return lib


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_loaded = False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_loaded
    with _lib_lock:
        if not _lib_loaded:
            try:
                _lib = _load()
            except Exception as e:  # noqa: BLE001
                logger.warning("shm native lib unavailable: %s", e)
                _lib = None
            _lib_loaded = True
        return _lib


class ShmStore:
    """Node-manager-side store (index + eviction + lifecycle)."""

    def __init__(self, capacity_bytes: int = 4 << 30,
                 prefix: Optional[str] = None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native shm store unavailable")
        self._lib = lib
        self.prefix = prefix or f"raytpu.{uuid.uuid4().hex[:12]}"
        self._h = ctypes.c_void_p(
            lib.shm_store_create(self.prefix.encode(), capacity_bytes))

    def put(self, oid_hex: str, data: bytes) -> Optional[str]:
        name = ctypes.create_string_buffer(_NAME_CAP)
        rc = self._lib.shm_store_put(self._h, oid_hex.encode(), data,
                                     len(data), name, _NAME_CAP)
        return name.value.decode() if rc == 0 else None

    def register(self, oid_hex: str, name: str, size: int) -> bool:
        return self._lib.shm_store_register(
            self._h, oid_hex.encode(), name.encode(), size) == 0

    def get(self, oid_hex: str) -> Optional[Tuple[str, int]]:
        name = ctypes.create_string_buffer(_NAME_CAP)
        size = ctypes.c_uint64()
        rc = self._lib.shm_store_get(self._h, oid_hex.encode(), name,
                                     _NAME_CAP, ctypes.byref(size))
        if rc != 0:
            return None
        return name.value.decode(), size.value

    def read(self, oid_hex: str) -> Optional[bytes]:
        """Copy an object out (used by the remote-pull streaming path)."""
        meta = self.get(oid_hex)
        if meta is None:
            return None
        name, size = meta
        return ShmClient.read_segment(name, size)

    def contains(self, oid_hex: str) -> bool:
        return bool(self._lib.shm_store_contains(self._h, oid_hex.encode()))

    def coldest(self) -> Optional[str]:
        """Least-recently-used object id (spill victim), or None if empty."""
        buf = ctypes.create_string_buffer(_NAME_CAP)
        if self._lib.shm_store_coldest(self._h, buf, _NAME_CAP) != 0:
            return None
        return buf.value.decode()

    def delete(self, oid_hex: str) -> bool:
        return self._lib.shm_store_delete(self._h, oid_hex.encode()) == 0

    def stats(self) -> Tuple[int, int]:
        return (self._lib.shm_store_used(self._h),
                self._lib.shm_store_count(self._h))

    def close(self):
        if self._h:
            self._lib.shm_store_destroy(self._h)
            self._h = None


class ShmClient:
    """Worker/driver-side access: direct create + read-only map."""

    @staticmethod
    def available() -> bool:
        return get_lib() is not None

    @staticmethod
    def create_segment(name: str, data: bytes) -> bool:
        lib = get_lib()
        if lib is None:
            return False
        return lib.shm_client_create(name.encode(), data, len(data)) == 0

    @staticmethod
    def create_segment_vectored(name: str, parts) -> bool:
        """Create+seal a segment from a list of buffers in one ``writev``
        — the fastest large-put path (full-page writes skip the page
        zeroing an mmap-then-copy pays; measured 2x). Returns True when
        the segment exists afterwards (including already-existing —
        immutable objects share content)."""
        path = f"/dev/shm/{name.lstrip('/')}"
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
        except FileExistsError:
            return True
        except OSError:
            return False
        try:
            todo = [memoryview(p).cast("B") if not isinstance(p, bytes)
                    else p for p in parts if len(p)]
            while todo:
                written = os.writev(fd, todo)
                # Partial writev: skip fully-written buffers, slice the rest.
                while todo and written >= len(todo[0]):
                    written -= len(todo[0])
                    todo.pop(0)
                if written and todo:
                    todo[0] = todo[0][written:]
            return True
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
            return False
        finally:
            os.close(fd)

    @staticmethod
    def unlink_segment(name: str) -> None:
        lib = get_lib()
        if lib is not None:
            lib.shm_client_unlink(name.encode())

    @staticmethod
    def read_segment(name: str, size: int) -> Optional[bytes]:
        lib = get_lib()
        if lib is None:
            return None
        ptr = lib.shm_client_map(name.encode(), size)
        if not ptr:
            return None
        try:
            return ctypes.string_at(ptr, size)
        finally:
            lib.shm_client_unmap(ptr, size)

    @staticmethod
    def map_segment_view(name: str, size: int) -> Optional[memoryview]:
        """Zero-copy read: mmap the segment and hand back a memoryview
        whose lifetime OWNS the mapping — slices (and numpy arrays
        deserialized over them) keep the map alive, and the mapping is
        released when the last view is garbage-collected. This is the
        ``get()`` data plane: the old ``read_segment`` copies the whole
        object into a bytes (the large-``get`` throughput collapse,
        ROADMAP item 3); deserialization over this view is copy-free
        because pickle-5 out-of-band buffers are sub-views. POSIX keeps
        the mapping valid after the store unlinks/evicts the segment, so
        readers never race eviction.

        Tradeoff (shared with plasma-style stores): a live reader view
        pins the unlinked segment's tmpfs pages until garbage-collected,
        so the store's used-bytes accounting can transiently undercount
        what /dev/shm actually holds. Readers that keep long-lived
        references to LARGE fetched objects keep their whole segment
        resident — copy out (``np.array(x)``) to release it early."""
        path = f"/dev/shm/{name.lstrip('/')}"
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return None
        try:
            mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        except (OSError, ValueError):
            return None
        finally:
            os.close(fd)
        return memoryview(mm)
