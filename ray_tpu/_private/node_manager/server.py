"""Node manager: the per-node daemon (raylet-equivalent).

Reference: ``src/ray/raylet`` (SURVEY.md C15-C21) — one process per node
running: a worker pool (spawn/reuse/idle-kill of Python worker processes,
reference ``worker_pool.h:216``), the local+cluster scheduler with spillback
(``cluster_task_manager.cc:44`` / ``local_task_manager.cc:121``), placement
bundle 2PC reservations (``placement_group_resource_manager.h``), and the
node object store + transfer endpoint (plasma + object manager, C12/C13; the
python dict store here is the interim data plane the C++ shm store replaces).
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private import metrics_defs as mdefs
from ray_tpu._private import rpc
from ray_tpu._private.scheduler import policies
from ray_tpu.protobuf import ray_tpu_pb2 as pb

logger = logging.getLogger(__name__)

HEARTBEAT_PERIOD_S = 0.5


def _heartbeat_period_s() -> float:
    """Env-tunable (RAY_TPU_HEARTBEAT_PERIOD_S) together with the GCS
    side's RAY_TPU_HEARTBEAT_TTL_S: co-tenant-loaded test boxes widen
    both instead of flaking on missed 3s liveness windows."""
    import os

    return float(os.environ.get("RAY_TPU_HEARTBEAT_PERIOD_S",
                                HEARTBEAT_PERIOD_S))
CLUSTER_VIEW_TTL_S = 1.0
IDLE_WORKER_TTL_S = 60.0
CHUNK_SIZE = 8 * 1024 * 1024


class _Worker:
    def __init__(self, worker_id: str, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.address: Optional[str] = None
        self.fast_address: str = ""  # framed-TCP task plane (fastpath.py)
        self.ready = threading.Event()
        self.leased_for: Optional[bytes] = None  # lease id
        self.is_actor_worker = False
        self.idle_since = time.monotonic()
        self.busy_since = 0.0  # set when leased (memory-monitor kill order)


def _child_pythonpath(env: Dict[str, str],
                      include_cwd: bool = False) -> str:
    """Module search path for child processes (workers, the node agent):
    they must import ray_tpu + pickled-by-reference modules from the same
    universe as this process."""
    parts = list(sys.path) + [env.get("PYTHONPATH", "")]
    if include_cwd:
        parts.append(os.getcwd())
    return os.pathsep.join(dict.fromkeys(filter(None, parts)))


class NodeManager:
    def __init__(self, gcs_address: str, port: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 node_id: Optional[str] = None):
        self.node_id = node_id or uuid.uuid4().hex
        self.gcs_address = gcs_address
        self.gcs = rpc.get_stub("GcsService", gcs_address)

        resources = dict(resources or {"CPU": float(os.cpu_count() or 4)})
        self.total = resources
        self.available = dict(resources)
        self._res_lock = threading.RLock()
        # Shares the resource lock so queued lease RPCs wake on release.
        self._res_cv = threading.Condition(self._res_lock)
        self._lease_queue_slots = threading.Semaphore(
            self.LEASE_QUEUE_SLOTS)
        # Instance-level TPU slot accounting (reference: per-GPU-slot
        # resource instances, common/scheduling/resource_instance_set.h):
        # whole-chip asks get concrete chip indices for TPU_VISIBLE_CHIPS.
        self._tpu_free: List[int] = list(range(int(resources.get("TPU", 0))))
        self._tpu_held: Dict[bytes, List[int]] = {}

        # object store: native shared-memory data plane (plasma-equivalent,
        # native/shm_store.cpp) with a python-dict fallback. The dict also
        # backs values received without a local shm segment.
        self._objects: Dict[bytes, bytes] = {}
        self._obj_lock = threading.RLock()
        self._shm = None
        # Spilling (reference: LocalObjectManager, local_object_manager.h:41):
        # instead of LRU-*dropping* under memory pressure, cold objects move
        # to disk and restore on access. The C++ store therefore gets an
        # unbounded capacity; the configured budget is enforced here by
        # spilling down from the high watermark to the low one.
        self._store_capacity = int(os.environ.get(
            "RAY_TPU_OBJECT_STORE_BYTES", 4 << 30))
        self._spill_dir = os.path.join(
            tempfile.gettempdir(), f"ray_tpu_spill_{self.node_id[:12]}")
        self._spilled: Dict[str, Tuple[str, int]] = {}  # oid -> (path, size)
        self._spill_lock = threading.Lock()
        self._spill_event = threading.Event()
        # Per-node agent fields (reference C21) — initialized BEFORE the
        # gRPC server / heartbeat thread go live so early RPC ticks can't
        # hit missing attributes.
        self._agent_enabled = \
            os.environ.get("RAY_TPU_DISABLE_AGENT") != "1"
        self._agent_proc: Optional[subprocess.Popen] = None
        self._agent_port = 0
        self._agent_respawn_after = 0.0
        self._agent_started_at = 0.0
        self._agent_starting = False
        # Envs seen before the agent finished starting: bounded queue,
        # flushed on start so a fresh node's first leases still pre-warm.
        self._pending_prewarm: List[bytes] = []
        try:
            from ray_tpu._private.shm import ShmStore

            self._shm = ShmStore(capacity_bytes=1 << 62)
        except Exception as e:  # noqa: BLE001
            logger.warning("native shm store unavailable (%s); "
                           "using in-memory store", e)

        # worker pool
        self._workers: Dict[str, _Worker] = {}
        self._idle: List[str] = []
        self._pool_lock = threading.RLock()
        self._spawning_task = 0   # in-flight spawns counted against the caps
        self._spawning_actor = 0

        # placement bundles (reference: placement_group_resource_manager.h).
        # Prepare holds the group's node-total demand; commit converts it to
        # per-bundle availability that PG-targeted leases charge against.
        self._prepared: Dict[bytes, Dict[str, float]] = {}
        self._pg_avail: Dict[bytes, Dict[int, Dict[str, float]]] = {}
        self._pg_totals: Dict[bytes, Dict[int, Dict[str, float]]] = {}
        # holder (lease or actor id) -> (group_id, bundle_index) it charged
        self._pg_holders: Dict[bytes, Tuple[bytes, int]] = {}
        # outstanding leases / actor resource holds
        self._leases: Dict[bytes, Tuple[str, Dict[str, float]]] = {}
        self._actor_demands: Dict[bytes, Tuple[str, Dict[str, float]]] = {}

        # cluster view: seeded/backstopped by a GetNodes poll, kept fresh
        # by NODE_RES availability deltas + NODE liveness events pushed
        # over pubsub (reference C9 ray_syncer gossip — push, not poll).
        self._view: List[pb.NodeInfo] = []
        self._view_ts = 0.0
        self._view_lock = threading.Lock()
        self._view_subscribed = False

        # Sender-side transfer caps (reference C13 PushManager,
        # push_manager.h:30): bound concurrent outbound object streams so
        # a hot object can't monopolize every handler thread + the NIC.
        self._push_slots = threading.BoundedSemaphore(
            int(os.environ.get("RAY_TPU_MAX_CONCURRENT_PUSHES", 8)))

        self._stop = threading.Event()
        # Observability: per-node tag for every series this daemon emits;
        # the per-process pusher ships them to the head TSDB (a no-op when
        # the GCS runs in this process — it samples the registry itself).
        # Set before the gRPC server goes live: lease RPCs touch both.
        self._mtags = {"node_id": self.node_id[:12]}
        self._queued_leases = 0
        self._queued_leases_lock = threading.Lock()
        # Pool sized above any single driver's submit concurrency: queued
        # lease RPCs briefly hold server threads (see _queue_for_resources).
        self._server, self.port = rpc.serve("NodeService", self, port=port,
                                            max_workers=128)
        self.address = f"127.0.0.1:{self.port}"
        # Binary object plane: owners flush put metadata / batches over
        # framed TCP instead of per-batch gRPC (the gRPC stack's CPU was
        # visible in the large-put path on small hosts).
        from ray_tpu._private import fastpath as _fastpath

        self._fast = _fastpath.FastServer(self._fast_handler)
        self.fast_address = self._fast.address

        info = pb.NodeInfo(node_id=self.node_id, address=self.address,
                           alive=True, fast_address=self.fast_address)
        for k, v in self.total.items():
            info.resources[k] = v
            info.available[k] = v
        for k, v in (labels or {}).items():
            info.labels[k] = v
        self.labels = dict(labels or {})
        # The very first RPC to a GCS that may have started milliseconds
        # ago: retry briefly on connection refusal (its gRPC listener can
        # lag the constructor's return under load) instead of failing a
        # node bootstrap on a startup race.
        deadline = time.monotonic() + 5.0
        while True:
            try:
                self.gcs.RegisterNode(pb.RegisterNodeRequest(info=info))
                break
            except Exception:  # noqa: BLE001 — UNAVAILABLE during startup
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)
        from ray_tpu._private import metrics_pusher, xla_monitor

        metrics_pusher.ensure_pusher(gcs_address,
                                     labels={"role": "node_manager"})
        xla_monitor.connect(gcs_address, node_id=self.node_id)
        threading.Thread(target=self._metrics_loop, daemon=True,
                         name="nm-metrics").start()

        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True, name="nm-heartbeat")
        self._hb_thread.start()
        threading.Thread(target=self._view_subscriber_loop, daemon=True,
                         name="nm-view-sub").start()
        # Prestart workers so first leases don't pay process-spawn latency
        # (reference: worker pool prestart, worker_pool.h:216).
        threading.Thread(target=self._prestart_workers, daemon=True).start()
        # Memory monitor (reference: memory_monitor.h:52): sheds the newest
        # leased task worker under host memory pressure so the OS OOM killer
        # never picks a victim at random. Kill cause surfaces through the
        # normal worker-crash retry path.
        self._mem_threshold = float(os.environ.get(
            "RAY_TPU_MEMORY_USAGE_THRESHOLD", 0.95))
        self._mem_usage_file = os.environ.get("RAY_TPU_MEMORY_USAGE_FILE", "")
        self.oom_kills = 0
        threading.Thread(target=self._memory_monitor_loop, daemon=True,
                         name="nm-memmon").start()
        if self._shm is not None:
            threading.Thread(target=self._spill_loop, daemon=True,
                             name="nm-spill").start()
        # Per-node agent (reference C21, raylet/agent_manager.h): spawned
        # as a subprocess, supervised (respawned) from the heartbeat loop,
        # does runtime-env pre-warm + node stats. Disabled via env for
        # tests that count processes.
        if self._agent_enabled:
            self._launch_agent()

    def _prestart_workers(self):
        n = min(int(self.total.get("CPU", 1)), 4)
        workers = []
        for _ in range(n):
            if self._stop.is_set():
                return
            workers.append(self._spawn_worker())
        for w in workers:
            if w.ready.wait(30) and not self._stop.is_set():
                with self._pool_lock:
                    if w.worker_id not in self._idle and w.leased_for is None:
                        self._idle.append(w.worker_id)

    # ------------------------------------------------------------ resources
    def _try_acquire(self, demand: Dict[str, float],
                     holder: Optional[bytes] = None) -> bool:
        with self._res_lock:
            if all(self.available.get(k, 0.0) + 1e-9 >= v
                   for k, v in demand.items()):
                for k, v in demand.items():
                    self.available[k] = self.available.get(k, 0.0) - v
                n_chips = int(demand.get("TPU", 0))
                if holder is not None and n_chips >= 1 and \
                        n_chips == demand.get("TPU"):
                    self._tpu_held[holder] = \
                        [self._tpu_free.pop() for _ in range(n_chips)]
                return True
            return False

    def _chips_for(self, holder: bytes) -> List[int]:
        with self._res_lock:
            return list(self._tpu_held.get(holder, []))

    def _release(self, demand: Dict[str, float],
                 holder: Optional[bytes] = None):
        with self._res_cv:
            for k, v in demand.items():
                self.available[k] = min(
                    self.available.get(k, 0.0) + v, self.total.get(k, 0.0))
            if holder is not None:
                self._tpu_free.extend(self._tpu_held.pop(holder, []))
            self._res_cv.notify_all()  # wake queued lease requests

    def _acquire_from_bundle(self, group_id: bytes, bundle_index: int,
                             demand: Dict[str, float],
                             holder: bytes) -> Tuple[bool, str]:
        """Charge ``demand`` against a committed bundle's reservation instead
        of free node capacity (reference:
        ``placement_group_resource_manager.h`` — bundles own CPU_group_...
        resource instances; here they own per-bundle availability maps).

        Chip slots were debited from ``available`` at prepare time but left
        in ``_tpu_free``; a PG lease claims its physical slots here.
        """
        with self._res_lock:
            bundles = self._pg_avail.get(group_id)
            if bundles is None:
                return False, "pg-unknown"
            indices = [bundle_index] if bundle_index >= 0 else sorted(bundles)
            for i in indices:
                avail = bundles.get(i)
                if avail is None:
                    continue
                if all(avail.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()):
                    for k, v in demand.items():
                        avail[k] = avail.get(k, 0.0) - v
                    n_chips = int(demand.get("TPU", 0))
                    if n_chips >= 1 and n_chips == demand.get("TPU"):
                        self._tpu_held[holder] = \
                            [self._tpu_free.pop() for _ in range(n_chips)]
                    self._pg_holders[holder] = (group_id, i)
                    return True, ""
            totals = self._pg_totals.get(group_id, {})
            fits_ever = any(
                all(t.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())
                for i, t in totals.items()
                if bundle_index < 0 or i == bundle_index)
            return False, ("pg-wait" if fits_ever else "infeasible")

    def _release_pg_holder(self, holder: bytes,
                           demand: Dict[str, float]) -> bool:
        """Return a PG lease/actor charge to its bundle. False if ``holder``
        never charged a bundle (caller falls back to node release). If the
        group was removed while the holder ran, its share was the only part
        of the reservation not yet returned to the node — credit it now."""
        with self._res_lock:
            key = self._pg_holders.pop(holder, None)
            if key is None:
                return False
            self._tpu_free.extend(self._tpu_held.pop(holder, []))
            group_id, idx = key
            bundles = self._pg_avail.get(group_id)
            if bundles is None or idx not in bundles:
                for k, v in demand.items():
                    self.available[k] = min(
                        self.available.get(k, 0.0) + v, self.total.get(k, 0.0))
                return True
            avail = bundles[idx]
            for k, v in demand.items():
                avail[k] = avail.get(k, 0.0) + v
            return True

    def _fast_handler(self, kind: int, payload: bytes) -> bytes:
        """Binary object plane (fastpath.py): put-batch flushes (sync
        large-put registration + the flusher's batches) skip the gRPC
        stack — measurable CPU per call on small hosts."""
        from ray_tpu._private import fastpath

        if kind == fastpath.KIND_PUT_BATCH:
            req = pb.PutObjectBatchRequest()
            req.ParseFromString(payload)
            return self.PutObjectBatch(req, None).SerializeToString()
        raise ValueError(f"unknown fastpath frame kind {kind}")

    def _heartbeat_loop(self):
        from ray_tpu._private import chaos

        seq = 0
        while not self._stop.wait(_heartbeat_period_s()):
            seq += 1
            # Chaos site: ``drop_node_hb`` skips this tick's GCS send —
            # the local bookkeeping below still runs, so the injected
            # fault is exactly a lost heartbeat, driving GCS liveness
            # reaping without wedging the node.
            directive = chaos.inject("node_heartbeat",
                                     node=self.node_id) or {}
            if not directive.get("drop"):
                req = pb.HeartbeatRequest(node_id=self.node_id, seq=seq)
                with self._res_lock:
                    for k, v in self.available.items():
                        req.available[k] = v
                try:
                    reply = self.gcs.Heartbeat(req, timeout=2)
                    if not reply.ok:
                        # GCS restarted / lost us: re-register.
                        info = pb.NodeInfo(node_id=self.node_id,
                                           address=self.address,
                                           alive=True,
                                           fast_address=self.fast_address)
                        for k, v in self.total.items():
                            info.resources[k] = v
                        with self._res_lock:
                            for k, v in self.available.items():
                                info.available[k] = v
                        for k, v in self.labels.items():
                            info.labels[k] = v
                        self.gcs.RegisterNode(
                            pb.RegisterNodeRequest(info=info))
                except Exception:  # noqa: BLE001
                    pass
            self._reap_idle_workers()
            self._check_dead_workers()
            self._check_agent()

    def _metrics_loop(self):
        """Dedicated sampling thread: gauge refreshes must never ride the
        heartbeat loop — under GIL saturation (worker spawn storms, task
        fan-outs) the extra per-tick python work delayed heartbeat sends
        past the 3s liveness threshold and got healthy nodes marked dead."""
        from ray_tpu._private import metrics_pusher

        interval = max(metrics_pusher.push_interval_s(), 1.0)
        while not self._stop.wait(interval):
            self._sample_node_metrics()

    def _sample_node_metrics(self):
        """Refresh this node's gauges each heartbeat tick (worker-pool
        states, lease-queue depth, store fill, host vitals)."""
        try:
            with self._pool_lock:
                total = len(self._workers)
                idle = len(self._idle)
                busy = sum(1 for w in self._workers.values()
                           if w.leased_for is not None)
            for state, count in (("total", total), ("idle", idle),
                                 ("busy", busy)):
                mdefs.NODE_WORKERS.set(count, tags={**self._mtags,
                                                    "state": state})
            mdefs.NODE_LEASE_QUEUE.set(self._queued_leases,
                                       tags=self._mtags)
            if self._shm is not None:
                used, count = self._shm.stats()
                mdefs.STORE_USED_BYTES.set(used, tags=self._mtags)
                mdefs.STORE_OBJECTS.set(count, tags=self._mtags)
            # Host vitals (mem/load/disk) are published by the node
            # AGENT's vitals loop only — a second publisher here would
            # double-count the host under agg=sum queries.
        except Exception:  # noqa: BLE001 — sampling must never kill the
            pass           # heartbeat loop

    # ------------------------------------------------------------- agent
    AGENT_START_GRACE_S = 60.0

    def _launch_agent(self) -> None:
        """Start _start_agent at most once at a time: without the flag a
        slow Popen lets the supervisor double-spawn and leak the loser."""
        if self._agent_starting or self._stop.is_set():
            return
        self._agent_starting = True
        threading.Thread(target=self._start_agent, daemon=True,
                         name="nm-agent-start").start()

    def _start_agent(self) -> None:
        """Spawn the per-node agent subprocess and read its port."""
        try:
            self._start_agent_inner()
        finally:
            self._agent_starting = False

    def _start_agent_inner(self) -> None:
        import sys

        if self._stop.is_set():
            return
        self._agent_started_at = time.monotonic()
        env = dict(os.environ)
        # The agent must import ray_tpu from wherever this process got it
        # (same rule as worker spawns).
        env["PYTHONPATH"] = _child_pythonpath(env)
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.agent",
                 "--gcs-address", self.gcs_address,
                 "--node-id", self.node_id,
                 "--spill-dir", self._spill_dir],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env)
        except Exception:  # noqa: BLE001
            # _check_agent retries after the respawn window (a one-off
            # fork failure must not kill supervision for good).
            logger.exception("node agent spawn failed")
            self._agent_respawn_after = time.monotonic() + 5.0
            return
        self._agent_proc = proc
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not self._stop.is_set():
            line = proc.stdout.readline().strip()
            if line.startswith("AGENT_PORT="):
                self._agent_port = int(line.split("=", 1)[1])
                if self._stop.is_set():
                    break
                pending, self._pending_prewarm = \
                    self._pending_prewarm[-16:], []
                for blob in pending:
                    self._prewarm_runtime_env(blob)
                return
            if not line and proc.poll() is not None:
                return
        # Stopped (or timed out) mid-start: don't orphan the subprocess.
        if self._stop.is_set():
            try:
                proc.terminate()
            except Exception:  # noqa: BLE001
                pass

    def _check_agent(self) -> None:
        """Respawn a dead/hung/never-started agent (reference AgentManager
        supervision), rate-limited so a crash loop doesn't spin."""
        if not self._agent_enabled or self._stop.is_set() \
                or self._agent_starting:
            return
        now = time.monotonic()
        proc = self._agent_proc
        if proc is not None and proc.poll() is None:
            if self._agent_port:
                return
            # Alive but never reported a port: give it the start grace,
            # then treat as hung and recycle.
            if now - self._agent_started_at < self.AGENT_START_GRACE_S:
                return
            try:
                proc.terminate()
            except Exception:  # noqa: BLE001
                pass
        if now < self._agent_respawn_after:
            return
        self._agent_respawn_after = now + 5.0
        self._agent_proc = None
        self._agent_port = 0
        if proc is not None:
            logger.warning("node agent died/hung (rc=%s); respawning",
                           proc.returncode)
        self._launch_agent()

    def _prewarm_runtime_env(self, runtime_env_blob: bytes) -> None:
        """Forward a lease's runtime env to the agent so the venv build /
        package download overlaps with placement (fire-and-forget)."""
        if not runtime_env_blob or not self._agent_enabled:
            return
        try:
            renv = pickle.loads(bytes(runtime_env_blob))
        except Exception:  # noqa: BLE001
            return
        # Ask the plugin registry which fields need building rather than
        # hardcoding them — a new plugin (conda's long builds most of all)
        # must be prewarmable without touching this gate.
        from ray_tpu._private.runtime_env import plugin as plugin_mod

        if not any(p.prewarmable and renv.get(p.name)
                   for p in plugin_mod.plugins_for(renv)):
            return  # env_vars-only: nothing to build, no thread to spawn
        if not self._agent_port:
            if len(self._pending_prewarm) < 16:
                self._pending_prewarm.append(bytes(runtime_env_blob))
            return

        def post():
            try:
                import json as _json
                import urllib.request

                req = urllib.request.Request(
                    f"http://127.0.0.1:{self._agent_port}"
                    "/runtime_env/prewarm",
                    data=_json.dumps(renv).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=5).read()
            except Exception:  # noqa: BLE001 — pre-warm is best-effort
                pass

        threading.Thread(target=post, daemon=True).start()

    def _view_subscriber_loop(self):
        """Consume NODE_RES availability deltas + NODE liveness events
        (reference C9: ray_syncer's push-based resource view). While the
        stream is live the GetNodes poll drops to a slow backstop."""
        while not self._stop.is_set():
            try:
                stream = self.gcs.Subscribe(pb.SubscribeRequest(
                    channels=["NODE_RES", "NODE"],
                    subscriber_id=f"nm-{self.node_id[:12]}"),
                    timeout=3600.0)
                self._view_subscribed = True
                for msg in stream:
                    if self._stop.is_set():
                        return
                    try:
                        ev = pickle.loads(msg.data)
                    except Exception:  # noqa: BLE001
                        continue
                    if msg.channel == "NODE_RES":
                        # Copy-on-write: snapshots handed out by
                        # _cluster_view share these messages, so patch a
                        # fresh copy instead of mutating one a scheduler
                        # thread may be iterating.
                        with self._view_lock:
                            for i, n in enumerate(self._view):
                                if n.node_id == ev["node_id"]:
                                    cp = pb.NodeInfo()
                                    cp.CopyFrom(n)
                                    for k, v in ev["available"].items():
                                        cp.available[k] = v
                                    self._view[i] = cp
                                    break
                    else:  # NODE liveness change: force a full refresh
                        self._view_ts = 0.0
            except Exception:  # noqa: BLE001
                pass
            finally:
                self._view_subscribed = False
            if self._stop.wait(1.0):
                return

    def _cluster_view(self) -> List[pb.NodeInfo]:
        now = time.monotonic()
        ttl = (10 * CLUSTER_VIEW_TTL_S if self._view_subscribed
               else CLUSTER_VIEW_TTL_S)
        if now - self._view_ts > ttl:
            try:
                fresh = list(
                    self.gcs.GetNodes(pb.GetNodesRequest(), timeout=2).nodes)
                with self._view_lock:
                    self._view = fresh
                    self._view_ts = now
            except Exception:  # noqa: BLE001
                pass
        with self._view_lock:
            return list(self._view)

    # ------------------------------------------------------------ worker pool
    def _spawn_worker(self) -> _Worker:
        worker_id = uuid.uuid4().hex
        cmd = [
            sys.executable, "-m", "ray_tpu._private.workers.default_worker",
            "--node-address", self.address,
            "--gcs-address", self.gcs_address,
            "--worker-id", worker_id,
            "--node-id", self.node_id,
        ]
        env = dict(os.environ)
        # Workers must resolve pickled-by-reference functions from the same
        # module universe as the submitting process (includes pytest's
        # sys.path injections when the node manager runs in a test process).
        env["PYTHONPATH"] = _child_pythonpath(env, include_cwd=True)
        if not self.total.get("TPU"):
            # CPU-only node: skip the TPU PJRT plugin registration in
            # sitecustomize (it imports jax at interpreter start, ~2s per
            # worker process).
            env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(cmd, env=env)
        worker = _Worker(worker_id, proc)
        with self._pool_lock:
            self._workers[worker_id] = worker
        return worker

    def _pop_worker(self, timeout_s: float = 30.0,
                    for_actor: bool = False) -> Optional[_Worker]:
        """Reference: WorkerPool::PopWorker (worker_pool.cc:1355).

        Task-worker spawn is capped (reference: maximum_startup_concurrency):
        a burst of zero-CPU leases must not fork-bomb the host — beyond the
        cap the lease waits briefly for a worker to free and otherwise
        retries from the client with backoff. Dedicated actor workers count
        against a separate, much larger cap (actors legitimately number in
        the dozens; their admission is governed by resources, not the pool).
        """
        if for_actor:
            cap = int(os.environ.get("RAY_TPU_MAX_ACTOR_WORKERS", 128))
        else:
            # 2x CPU: the headroom matters for nested tasks — parents
            # blocked in ray.get occupy workers, and a 1x cap would
            # livelock a full-width nested fan-out (workers are not
            # released while blocked).
            cap = int(os.environ.get(
                "RAY_TPU_MAX_WORKERS",
                max(4, int(self.total.get("CPU", 4)) * 2)))
        start = time.monotonic()
        reserved = False
        while True:
            with self._pool_lock:
                while self._idle:
                    wid = self._idle.pop()
                    w = self._workers.get(wid)
                    if w and w.proc.poll() is None:
                        return w
                if for_actor:
                    used = sum(1 for w in self._workers.values()
                               if w.is_actor_worker)
                    used += self._spawning_actor
                else:
                    used = sum(1 for w in self._workers.values()
                               if not w.is_actor_worker)
                    used += self._spawning_task
                if used < cap:
                    # Reserve the slot under the lock — concurrent lease
                    # RPCs must not all pass the check before any spawn
                    # registers (that is the fork-bomb the cap prevents).
                    if for_actor:
                        self._spawning_actor += 1
                    else:
                        self._spawning_task += 1
                    reserved = True
            if reserved:
                break
            if time.monotonic() - start > 1.0:  # wait ≤1s at the cap
                return None
            time.sleep(0.005)
        try:
            worker = self._spawn_worker()
            if for_actor:
                worker.is_actor_worker = True
        finally:
            with self._pool_lock:
                if for_actor:
                    self._spawning_actor -= 1
                else:
                    self._spawning_task -= 1
        if worker.ready.wait(timeout_s):
            return worker
        return None

    def _reap_idle_workers(self):
        now = time.monotonic()
        reaped = []
        with self._pool_lock:
            keep = []
            for wid in self._idle:
                w = self._workers.get(wid)
                if w is None or w.proc.poll() is not None:
                    continue
                if now - w.idle_since > IDLE_WORKER_TTL_S:
                    w.proc.terminate()
                    self._workers.pop(wid, None)
                    reaped.append(wid)
                else:
                    keep.append(wid)
            self._idle = keep
        for wid in reaped:
            try:
                self.gcs.ReapHolder(
                    pb.ReapHolderRequest(holder_id=wid), timeout=5)
            except Exception:  # noqa: BLE001
                pass

    def _check_dead_workers(self):
        """Detect crashed actor workers and hand the restart decision to the
        GCS (reference: raylet worker-death notification →
        GcsActorManager::OnWorkerDead)."""
        with self._pool_lock:
            dead = [w for w in self._workers.values()
                    if w.proc.poll() is not None]
            for w in dead:
                self._workers.pop(w.worker_id, None)
                if w.worker_id in self._idle:
                    self._idle.remove(w.worker_id)
        for w in dead:
            # A dead worker's refcounts would pin objects forever: reap its
            # holder at the GCS (reference: refs tied to owner liveness).
            try:
                self.gcs.ReapHolder(
                    pb.ReapHolderRequest(holder_id=w.worker_id), timeout=5)
            except Exception:  # noqa: BLE001
                pass
            for actor_id, (wid, demand) in list(self._actor_demands.items()):
                if wid != w.worker_id:
                    continue
                del self._actor_demands[actor_id]
                if not self._release_pg_holder(actor_id, demand):
                    self._release(demand, holder=actor_id)
                try:
                    reply = self.gcs.GetActor(
                        pb.GetActorRequest(actor_id=actor_id), timeout=5)
                    if reply.found and reply.info.state == "ALIVE" \
                            and reply.info.node_id == self.node_id:
                        info = reply.info
                        info.state = "RESTARTING"
                        info.death_cause = "worker process died"
                        self.gcs.UpdateActor(
                            pb.UpdateActorRequest(info=info), timeout=5)
                except Exception:  # noqa: BLE001
                    pass

    def AnnounceWorker(self, request, context):
        with self._pool_lock:
            w = self._workers.get(request.worker_id)
            if w is None:
                # Unknown worker (e.g. an orphan from a dead node manager that
                # hit a reused port): reject — it will exit on its own.
                logger.warning("rejecting unknown worker %s",
                               request.worker_id[:8])
                return pb.Empty()
            w.address = request.address
            w.fast_address = request.fast_address
            w.ready.set()
        return pb.Empty()

    # ------------------------------------------------------------ leases
    def RequestWorkerLease(self, request, context):
        """Reference: NodeManager::HandleRequestWorkerLease
        (raylet/node_manager.cc:1868) + ClusterTaskManager scheduling."""
        spec = request.spec
        demand = dict(spec.resources)
        lease_id = uuid.uuid4().bytes
        if spec.runtime_env:
            self._prewarm_runtime_env(spec.runtime_env)
        if spec.placement_group_id:
            # PG-targeted: charge the bundle reservation; never spill back —
            # the bundle lives here or nowhere (bundle_scheduling_policy.h).
            ok, err = self._acquire_from_bundle(
                bytes(spec.placement_group_id), spec.pg_bundle_index,
                demand, lease_id)
            if not ok:
                return pb.LeaseReply(granted=False, error=err)
            worker = self._pop_worker()
            if worker is None:
                self._release_pg_holder(lease_id, demand)
                return pb.LeaseReply(granted=False,
                                     error="worker start timeout")
            worker.leased_for = lease_id
            worker.busy_since = time.monotonic()
            with self._pool_lock:
                if worker.worker_id in self._idle:
                    self._idle.remove(worker.worker_id)
            self._leases[lease_id] = (worker.worker_id, demand)
            return pb.LeaseReply(granted=True,
                                 worker_address=worker.address,
                                 worker_fast_address=worker.fast_address,
                                 worker_id=worker.worker_id,
                                 tpu_chips=self._chips_for(lease_id))
        selector = policies.parse_label_selector(spec.label_selector)
        if selector is not None:
            return self._lease_with_labels(spec, demand, lease_id, selector)
        if spec.strategy == "SPREAD":
            # Min-utilization placement (reference: spread_scheduling_policy):
            # compare POST-charge utilization — what each node would look
            # like with this task on it — or an idle-but-small local node
            # swallows a whole fan-out serially. A small margin damps
            # spillback ping-pong between nodes with stale views.
            others = [n for n in self._cluster_view()
                      if n.node_id != self.node_id]
            best = policies.pick_node_spread(others, demand)
            if best is not None:
                me = pb.NodeInfo(node_id=self.node_id, alive=True)
                with self._res_lock:
                    for k, v in self.total.items():
                        me.resources[k] = v
                    for k, v in self.available.items():
                        me.available[k] = v
                best_node = next(n for n in others if n.node_id == best)
                if policies.util_after(best_node, demand) + 0.02 < \
                        policies.util_after(me, demand):
                    return pb.LeaseReply(granted=False,
                                         spillback_node_id=best,
                                         spillback_address=best_node.address)
        if self._try_acquire(demand, holder=lease_id):
            return self._grant_lease(lease_id, demand)
        if spec.affinity_node_id and not spec.affinity_soft:
            # Hard node affinity (NodeAffinitySchedulingStrategy): never
            # spill; the task waits for local resources, or fails if this
            # node can never hold the demand.
            if not all(self.total.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()):
                return pb.LeaseReply(granted=False, error="infeasible")
            return self._queue_for_resources(lease_id, demand)
        # Spillback: pick another node from the cluster view.
        nodes = [n for n in self._cluster_view() if n.node_id != self.node_id]
        picker = (policies.pick_node_spread if spec.strategy == "SPREAD"
                  else policies.pick_node_hybrid)
        target = picker(nodes, demand)
        if target is None:
            if not policies.feasible_anywhere(self._cluster_view(), demand):
                return pb.LeaseReply(granted=False, error="infeasible")
            # Nowhere else to go right now: queue locally instead of making
            # the client poll-with-backoff (the idle gaps between client
            # retries were the dominant cost of task fan-out).
            return self._queue_for_resources(lease_id, demand)
        addr = next(n.address for n in nodes if n.node_id == target)
        return pb.LeaseReply(granted=False, spillback_node_id=target,
                             spillback_address=addr)

    def _lease_with_labels(self, spec, demand: Dict[str, float],
                           lease_id: bytes, selector: Dict[str, dict]):
        """Node-label scheduling (reference: node-label scheduling policy):
        hard selectors gate eligibility, soft selectors rank, then the base
        policy places among the surviving tier. The TPU-native use is
        targeting one ICI slice (``hard={"tpu-slice": ...}``)."""
        hard = selector.get("hard") or {}
        soft = selector.get("soft") or {}
        local_hard = policies.match_labels(self.labels, hard)
        local_soft = local_hard and policies.match_labels(self.labels, soft)
        view = self._cluster_view()
        others = [n for n in view if n.node_id != self.node_id]
        picker = (policies.pick_node_spread if spec.strategy == "SPREAD"
                  else policies.pick_node_hybrid)
        if soft:
            if local_soft and self._try_acquire(demand, holder=lease_id):
                return self._grant_lease(lease_id, demand)
            # Prefer a soft-matching node with capacity right now; when the
            # soft tier has no capacity anywhere, fall through to the hard
            # tier instead of spilling forever (soft is a preference, not a
            # requirement — a soft-only selector must not livelock).
            soft_fit = [n for n in others if n.alive
                        and policies.match_labels(dict(n.labels), hard)
                        and policies.match_labels(dict(n.labels), soft)]
            target = picker(soft_fit, demand)
            if target is not None:
                addr = next(n.address for n in others
                            if n.node_id == target)
                return pb.LeaseReply(granted=False,
                                     spillback_node_id=target,
                                     spillback_address=addr)
        if local_hard and self._try_acquire(demand, holder=lease_id):
            return self._grant_lease(lease_id, demand)
        hard_fit = [n for n in others if n.alive
                    and policies.match_labels(dict(n.labels), hard)]
        target = picker(hard_fit, demand)
        if target is not None:
            addr = next(n.address for n in others if n.node_id == target)
            return pb.LeaseReply(granted=False, spillback_node_id=target,
                                 spillback_address=addr)
        if not policies.feasible_with_labels(view, demand, selector):
            return pb.LeaseReply(granted=False, error="infeasible")
        if local_hard:
            return self._queue_for_resources(lease_id, demand)
        # Eligible nodes exist but are momentarily full: client backs off.
        return pb.LeaseReply(granted=False)

    def _grant_lease(self, lease_id: bytes, demand: Dict[str, float]):
        worker = self._pop_worker()
        if worker is None:
            self._release(demand, holder=lease_id)
            return pb.LeaseReply(granted=False,
                                 error="worker start timeout")
        worker.leased_for = lease_id
        worker.busy_since = time.monotonic()
        with self._pool_lock:
            if worker.worker_id in self._idle:
                self._idle.remove(worker.worker_id)
        # Stash demand so ReturnWorker releases it.
        self._leases[lease_id] = (worker.worker_id, demand)
        mdefs.NODE_LEASES_GRANTED.inc(tags=self._mtags)
        return pb.LeaseReply(granted=True,
                             worker_address=worker.address,
                             worker_fast_address=worker.fast_address,
                             worker_id=worker.worker_id,
                             tpu_chips=self._chips_for(lease_id))

    LEASE_QUEUE_WAIT_S = 2.0
    # Cap on concurrently-queued lease RPCs: each holds a server thread,
    # and filling the whole pool with them would starve ReturnWorker — the
    # very RPC that frees the resources they wait for.
    LEASE_QUEUE_SLOTS = 32

    def _queue_for_resources(self, lease_id: bytes,
                             demand: Dict[str, float]):
        """Hold the lease RPC briefly until resources free up (reference:
        the raylet queues lease requests; clients never poll). Bounded in
        duration AND in concurrency — on either limit the client's retry
        loop takes over."""
        if not self._lease_queue_slots.acquire(blocking=False):
            return pb.LeaseReply(granted=False)
        with self._queued_leases_lock:
            self._queued_leases += 1
        try:
            deadline = time.monotonic() + self.LEASE_QUEUE_WAIT_S
            with self._res_cv:
                while not self._stop.is_set() and \
                        time.monotonic() < deadline:
                    if self._try_acquire(demand, holder=lease_id):
                        break
                    self._res_cv.wait(0.05)
                else:
                    return pb.LeaseReply(granted=False)
            return self._grant_lease(lease_id, demand)
        finally:
            with self._queued_leases_lock:
                self._queued_leases -= 1
            self._lease_queue_slots.release()

    def ReturnWorker(self, request, context):
        lease_id = request.lease_id
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            # Fall back to any lease held by that worker.
            for lid, (wid, demand) in list(self._leases.items()):
                if wid == request.worker_id:
                    lease = self._leases.pop(lid)
                    lease_id = lid
                    break
        if lease is not None:
            _, demand = lease
            # Release exactly this lease's resources and chip slots. (Chips
            # held by live actors are keyed by actor_id and must NOT be
            # reclaimed here — see resource_instance_set.h semantics.)
            if not self._release_pg_holder(lease_id, demand):
                self._release(demand, holder=lease_id)
        with self._pool_lock:
            w = self._workers.get(request.worker_id)
            if w and w.proc.poll() is None and not w.is_actor_worker:
                w.leased_for = None
                w.idle_since = time.monotonic()
                if request.worker_id not in self._idle:
                    self._idle.append(request.worker_id)
        return pb.Empty()

    def CreateActorOnNode(self, request, context):
        """Lease a dedicated worker and instantiate the actor on it
        (reference: GcsActorScheduler raylet leg, gcs_actor_scheduler.cc:107)."""
        info = request.info
        spec = pickle.loads(info.spec)
        demand = dict(spec.get("resources", {}))
        pg = spec.get("pg")
        if pg is not None:
            ok, err = self._acquire_from_bundle(
                pg[0], pg[1], demand, bytes(info.actor_id))
            if not ok:
                return pb.CreateActorOnNodeReply(
                    ok=False, error=f"insufficient resources ({err})")
        elif not self._try_acquire(demand, holder=bytes(info.actor_id)):
            return pb.CreateActorOnNodeReply(
                ok=False, error="insufficient resources")
        worker = self._pop_worker(for_actor=True)
        if worker is None:
            if not self._release_pg_holder(bytes(info.actor_id), demand):
                self._release(demand, holder=bytes(info.actor_id))
            return pb.CreateActorOnNodeReply(ok=False,
                                             error="worker start timeout")
        worker.is_actor_worker = True
        with self._pool_lock:
            if worker.worker_id in self._idle:
                self._idle.remove(worker.worker_id)
        self._actor_demands[info.actor_id] = (worker.worker_id, demand)
        stub = rpc.get_stub("WorkerService", worker.address)
        info.node_id = self.node_id
        info.address = worker.address
        info.fast_address = worker.fast_address
        env = {}
        chips = self._chips_for(bytes(info.actor_id))
        if chips:
            env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, chips))
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,1,{len(chips)}"
        for k, v in spec.get("runtime_env", {}).get("env_vars", {}).items():
            env[k] = str(v)
        try:
            reply = stub.CreateActor(pb.CreateActorRequest(info=info, env=env),
                                     timeout=60)
        except Exception as e:  # noqa: BLE001
            self._actor_demands.pop(info.actor_id, None)
            if not self._release_pg_holder(bytes(info.actor_id), demand):
                self._release(demand, holder=bytes(info.actor_id))
            return pb.CreateActorOnNodeReply(ok=False, error=str(e))
        if not reply.ok:
            self._actor_demands.pop(info.actor_id, None)
            if not self._release_pg_holder(bytes(info.actor_id), demand):
                self._release(demand, holder=bytes(info.actor_id))
            return pb.CreateActorOnNodeReply(ok=False, error=reply.error)
        return pb.CreateActorOnNodeReply(ok=True,
                                         worker_address=worker.address,
                                         fast_address=worker.fast_address)

    # ------------------------------------------------------------ bundles
    def PrepareBundle(self, request, context):
        total_demand: Dict[str, float] = defaultdict(float)
        for b in request.bundles:
            for k, v in b.resources.items():
                total_demand[k] += v
        # A re-prepare for the same group supersedes the previous attempt;
        # release the stale reservation or it leaks (each prepare debits).
        stale = self._prepared.pop(request.group_id, None)
        if stale is not None:
            self._release(stale)
        if self._try_acquire(dict(total_demand)):
            self._prepared[request.group_id] = dict(total_demand)
            return pb.PrepareBundleReply(success=True)
        return pb.PrepareBundleReply(success=False)

    def CommitBundle(self, request, context):
        demand = self._prepared.pop(request.group_id, None)
        if demand is None:
            return pb.Empty()  # already cancelled or duplicate commit
        with self._res_lock:
            avail = self._pg_avail.setdefault(request.group_id, {})
            totals = self._pg_totals.setdefault(request.group_id, {})
            for b in request.bundles:
                avail[b.index] = dict(b.resources)
                totals[b.index] = dict(b.resources)
        return pb.Empty()

    def CancelBundle(self, request, context):
        demand = self._prepared.pop(request.group_id, None)
        if demand is not None:
            self._release(demand)
            return pb.Empty()
        with self._res_lock:
            avail = self._pg_avail.pop(request.group_id, None)
            self._pg_totals.pop(request.group_id, None)
        if avail is not None:
            # Return only the unconsumed share; outstanding PG leases return
            # their charges straight to the node when they finish
            # (_release_pg_holder group-gone branch).
            freed: Dict[str, float] = defaultdict(float)
            for res in avail.values():
                for k, v in res.items():
                    freed[k] += v
            self._release(dict(freed))
        return pb.Empty()

    # ----------------------------------------------------------- spilling
    SPILL_HIGH = 0.9  # spill starts above this fraction of the budget
    SPILL_LOW = 0.7   # ... and runs down to this fraction

    def _maybe_spill(self):
        """Signal the spill thread when the store exceeds its budget
        (reference: LocalObjectManager::SpillObjectsOfSize,
        local_object_manager.h:41 — spilling happens on background IO, so
        the put/get handler threads never stall on the disk drain)."""
        if self._shm is None:
            return
        used, _ = self._shm.stats()
        if used > self._store_capacity * self.SPILL_HIGH:
            self._spill_event.set()

    def _spill_loop(self):
        while not self._stop.is_set():
            if not self._spill_event.wait(0.25):
                continue
            self._spill_event.clear()
            self._drain_to_low_water()

    def _drain_to_low_water(self, min_free_bytes: int = 0):
        """Spill LRU-cold objects until usage falls to the low watermark
        (or low enough that ``min_free_bytes`` fits — an object larger
        than the watermark slack must still be admittable; reference:
        plasma SpillObjectsOfSize takes the needed size). The lock is
        taken per victim so concurrent restores/pulls interleave with the
        drain instead of blocking for its whole duration."""
        target = min(self._store_capacity * self.SPILL_LOW,
                     max(self._store_capacity - min_free_bytes, 0))
        try:
            os.makedirs(self._spill_dir, exist_ok=True)
        except OSError:
            return
        while not self._stop.is_set():
            used, _ = self._shm.stats()
            if used <= target:
                break
            with self._spill_lock:
                oid = self._shm.coldest()
                if oid is None:
                    break
                data = self._shm.read(oid)
                if data is None:
                    self._shm.delete(oid)
                    continue
                path = os.path.join(self._spill_dir, oid)
                tmp = f"{path}.tmp.{os.getpid()}"
                try:
                    with open(tmp, "wb") as f:
                        f.write(data)
                    os.replace(tmp, path)
                except OSError:
                    logger.exception("spill write failed; stopping spill")
                    break
                self._spilled[oid] = (path, len(data))
                self._shm.delete(oid)
                mdefs.STORE_SPILLED.inc(tags=self._mtags)
                mdefs.STORE_SPILLED_BYTES.inc(len(data), tags=self._mtags)

    def _restore_spilled(self, oid_hex: str) -> Optional[bytes]:
        """Bring a spilled object back (reference:
        ObjectManager restore-from-external-storage). Returns the bytes, or
        None if this object was never spilled here."""
        with self._spill_lock:
            meta = self._spilled.get(oid_hex)
            if meta is None:
                return None
            path, _ = meta
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                self._spilled.pop(oid_hex, None)
                return None
            if self._shm is not None and \
                    self._shm.put(oid_hex, data) is not None:
                self._spilled.pop(oid_hex, None)
                try:
                    os.unlink(path)
                except OSError:
                    pass
        mdefs.STORE_RESTORED.inc(tags=self._mtags)
        self._maybe_spill()  # the restore itself may breach the high water
        return data

    # ------------------------------------------------------ memory monitor
    def _memory_usage_fraction(self) -> float:
        if self._mem_usage_file:
            try:
                with open(self._mem_usage_file) as f:
                    return float(f.read().strip() or 0.0)
            except (OSError, ValueError):
                return 0.0
        try:  # cgroup v2 limit, when one is set
            with open("/sys/fs/cgroup/memory.current") as f:
                cur = int(f.read())
            with open("/sys/fs/cgroup/memory.max") as f:
                mx = f.read().strip()
            if mx != "max":
                return cur / max(int(mx), 1)
        except (OSError, ValueError):
            pass
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    info[k] = int(v.strip().split()[0])
            return 1.0 - info["MemAvailable"] / max(info["MemTotal"], 1)
        except (OSError, ValueError, KeyError):
            return 0.0

    def _memory_monitor_loop(self):
        while not self._stop.wait(0.25):
            if self._memory_usage_fraction() < self._mem_threshold:
                continue
            if self._shed_memory():
                # Give the freed memory time to show up before re-checking.
                self._stop.wait(1.0)

    def _shed_memory(self) -> bool:
        """Kill the newest leased non-actor worker (newest-first mirrors the
        reference policy of shedding retriable work before long-running
        work; the node doesn't see TaskSpecs, so retriability itself is
        decided by the owner's retry budget on the crash-retry path)."""
        with self._pool_lock:
            busy = [w for w in self._workers.values()
                    if w.leased_for is not None and not w.is_actor_worker
                    and w.proc.poll() is None]
            if not busy:
                return False
            victim = max(busy, key=lambda w: w.busy_since)
        logger.warning(
            "memory usage above threshold %.2f: killing newest task worker "
            "%s (reference memory_monitor policy)",
            self._mem_threshold, victim.worker_id)
        try:
            victim.proc.kill()
        except Exception:  # noqa: BLE001
            return False
        self.oom_kills += 1
        mdefs.NODE_OOM_KILLS.inc(tags=self._mtags)
        return True

    # ------------------------------------------------------------ objects
    def _store_object(self, request) -> Optional[int]:
        """Seat one object in the local store; returns its size, or None
        when it could not be stored (capacity even after spilling) — the
        caller must NOT register a directory location for a dropped
        object, or readers would spin fetching something that isn't there.

        Backpressure (reference: plasma's create-request queue): a
        capacity failure spills down to the low watermark synchronously
        and retries once before giving up.
        """
        size = request.size or len(request.data)
        oid_hex = request.object_id.hex()
        if request.shm_name and self._shm is not None:
            # Zero-copy put: the client already created+sealed the segment;
            # only the metadata is registered (plasma Create/Seal protocol).
            if not self._seat_with_backpressure(
                    lambda: self._shm.register(oid_hex, request.shm_name,
                                               request.size), size):
                logger.warning("store full: rejecting register of %s "
                               "(%d bytes)", oid_hex[:12], size)
                # Nothing indexes the client-created segment now: unlink
                # it or it leaks in /dev/shm forever.
                from ray_tpu._private.shm import ShmClient

                ShmClient.unlink_segment(request.shm_name)
                mdefs.STORE_PUTS.inc(tags={**self._mtags,
                                           "outcome": "rejected"})
                return None
        elif self._shm is not None and request.data:
            if not self._seat_with_backpressure(
                    lambda: self._shm.put(oid_hex,
                                          request.data) is not None, size):
                logger.warning("store full: rejecting put of %s "
                               "(%d bytes)", oid_hex[:12], size)
                mdefs.STORE_PUTS.inc(tags={**self._mtags,
                                           "outcome": "rejected"})
                return None
        else:
            with self._obj_lock:
                self._objects[request.object_id] = request.data
        # Counted only once the object actually seated — rejected puts
        # must not inflate the store-fill byte series.
        mdefs.STORE_PUT_BYTES.inc(size, tags=self._mtags)
        mdefs.STORE_PUTS.inc(tags={**self._mtags, "outcome": "ok"})
        return size

    def _seat_with_backpressure(self, attempt, size: int,
                                retries: int = 5) -> bool:
        """Run ``attempt()`` with spill-down retries: concurrent writers
        can consume freed space between a drain and the retry, so one
        retry is not enough under sustained pressure (plasma queues
        create requests; this bounded loop is the collapsed analog)."""
        if attempt():
            return True
        if size > self._store_capacity:
            # Can NEVER fit: draining would evict the entire store to
            # disk on every retry without ever succeeding.
            return False
        for _ in range(retries):
            self._drain_to_low_water(min_free_bytes=size)
            if attempt():
                return True
        return False

    def PutObject(self, request, context):
        size = self._store_object(request)
        if size is not None:
            try:
                self.gcs.UpdateObjectLocation(pb.ObjectLocationUpdate(
                    object_id=request.object_id, node_id=self.node_id,
                    added=True, size=size))
            except Exception:  # noqa: BLE001
                pass
        self._maybe_spill()
        return pb.PutObjectReply(rejected=size is None)

    def PutObjectBatch(self, request, context):
        """Amortized small-object puts (the driver's put flusher batches
        inline payloads into one RPC instead of an RPC per object; the
        directory registration rides one batched GCS RPC too)."""
        batch = pb.ObjectLocationBatch()
        rejected = []
        for item in request.items:
            size = self._store_object(item)
            rejected.append(size is None)
            if size is None:
                continue  # rejected at capacity: no location to register
            batch.updates.append(pb.ObjectLocationUpdate(
                object_id=item.object_id, node_id=self.node_id,
                added=True, size=size))
        try:
            self.gcs.UpdateObjectLocationsBatch(batch)
        except Exception:  # noqa: BLE001
            pass
        self._maybe_spill()
        return pb.PutObjectBatchReply(rejected=rejected)

    def GetObject(self, request, context):
        reply = self._get_object_inner(request)
        mdefs.STORE_GETS.inc(tags={
            **self._mtags, "outcome": "hit" if reply.found else "miss"})
        return reply

    def _get_object_inner(self, request):
        oid_hex = request.object_id.hex()
        if self._shm is not None:
            meta = self._shm.get(oid_hex)
            if meta is None and oid_hex in self._spilled:
                if request.metadata_only:
                    # Report presence without paying the restore.
                    size = self._spilled.get(oid_hex, (None, 0))[1]
                    return pb.GetObjectReply(found=True, size=size)
                data = self._restore_spilled(oid_hex)
                if data is not None:
                    meta = self._shm.get(oid_hex)
                    if meta is None:  # restore couldn't re-seat it in shm
                        return pb.GetObjectReply(found=True, data=data)
            if meta is not None:
                name, size = meta
                if request.metadata_only:
                    return pb.GetObjectReply(found=True, size=size)
                return pb.GetObjectReply(found=True, shm_name=name, size=size)
        with self._obj_lock:
            data = self._objects.get(request.object_id)
        if data is None:
            return pb.GetObjectReply(found=False)
        if request.metadata_only:
            return pb.GetObjectReply(found=True, size=len(data))
        return pb.GetObjectReply(found=True, data=data)

    def GetObjectsMeta(self, request, context):
        """Batched local readiness (reference: plasma Contains). One RPC
        answers every object a wait() is watching on this node."""
        found = []
        for oid in request.object_ids:
            hexid = oid.hex()
            ok = False
            if self._shm is not None:
                ok = self._shm.contains(hexid) or hexid in self._spilled
            if not ok:
                with self._obj_lock:
                    ok = oid in self._objects
            found.append(ok)
        return pb.GetObjectsMetaReply(found=found)

    def _read_object_bytes(self, object_id: bytes) -> Optional[bytes]:
        if self._shm is not None:
            data = self._shm.read(object_id.hex())
            if data is not None:
                return data
            # Spilled: serve straight from disk without churning the store
            # (remote pulls don't need the object resident locally).
            with self._spill_lock:
                meta = self._spilled.get(object_id.hex())
                if meta is not None:
                    try:
                        with open(meta[0], "rb") as f:
                            return f.read()
                    except OSError:
                        pass
        with self._obj_lock:
            return self._objects.get(object_id)

    def PullObject(self, request, context):
        """Chunked streaming transfer (reference: ObjectManager 64MB chunks,
        object_manager.h:117). Outbound streams are capped (PushManager
        analog, push_manager.h:30): a hot object fanned out to many nodes
        queues behind the slot limit instead of saturating every handler
        thread at once."""
        data = self._read_object_bytes(request.object_id)
        if data is None:
            yield pb.ObjectChunk(object_id=request.object_id, found=False,
                                 eof=True)
            return
        if not self._push_slots.acquire(timeout=60.0):
            # Saturated for a full minute: fail the pull; the client
            # retries another location or re-requests.
            yield pb.ObjectChunk(object_id=request.object_id, found=False,
                                 eof=True)
            return
        try:
            total = len(data)
            for off in range(0, max(total, 1), CHUNK_SIZE):
                chunk = data[off:off + CHUNK_SIZE]
                yield pb.ObjectChunk(object_id=request.object_id,
                                     total_size=total, offset=off,
                                     data=chunk, found=True,
                                     eof=off + CHUNK_SIZE >= total)
        finally:
            self._push_slots.release()

    def FreeObjects(self, request, context):
        with self._obj_lock:
            for oid in request.object_ids:
                self._objects.pop(oid, None)
        batch = pb.ObjectLocationBatch()
        for oid in request.object_ids:
            if self._shm is not None:
                self._shm.delete(oid.hex())
            with self._spill_lock:
                meta = self._spilled.pop(oid.hex(), None)
            if meta is not None:
                try:
                    os.unlink(meta[0])
                except OSError:
                    pass
            batch.updates.append(pb.ObjectLocationUpdate(
                object_id=oid, node_id=self.node_id, added=False))
        try:
            self.gcs.UpdateObjectLocationsBatch(batch)
        except Exception:  # noqa: BLE001
            pass
        return pb.Empty()

    # ------------------------------------------------------------ lifecycle
    def shutdown(self, graceful: bool = True):
        """Stop the node. ``graceful=False`` simulates a node crash: no drain
        notification, so the GCS health checker must discover the death."""
        self._stop.set()
        try:
            # Close the fastpath object plane first: a zombie listener
            # would keep accepting put registrations for a dead node.
            self._fast.close()
        except Exception:  # noqa: BLE001
            pass
        if graceful:
            try:
                self.gcs.DrainNode(pb.DrainNodeRequest(node_id=self.node_id),
                                   timeout=2)
            except Exception:  # noqa: BLE001
                pass
        # Kill twice with a grace gap so workers mid-spawn in the prestart
        # thread are also reaped.
        for _ in range(2):
            with self._pool_lock:
                workers = list(self._workers.values())
            for w in workers:
                try:
                    w.proc.terminate()
                except Exception:  # noqa: BLE001
                    pass
            time.sleep(0.1)
        if self._agent_proc is not None:
            try:
                self._agent_proc.terminate()
            except Exception:  # noqa: BLE001
                pass
        self._server.stop(grace=0.2)
        if self._shm is not None:
            try:
                self._shm.close()
            except Exception:  # noqa: BLE001
                pass
        import shutil

        shutil.rmtree(self._spill_dir, ignore_errors=True)


class _DummyProc:
    def __init__(self, pid: int):
        self.pid = pid

    def poll(self):
        try:
            os.kill(self.pid, 0)
            return None
        except OSError:
            return 1

    def terminate(self):
        try:
            os.kill(self.pid, 15)
        except OSError:
            pass


def main():  # pragma: no cover - run as subprocess
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--num-cpus", type=float, default=float(os.cpu_count() or 4))
    parser.add_argument("--num-tpus", type=float, default=-1.0,
                        help="-1 = auto-detect, 0 = explicitly none")
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    import json

    resources = {"CPU": args.num_cpus}
    labels = json.loads(args.labels)
    if args.num_tpus > 0:
        resources["TPU"] = args.num_tpus
    elif args.num_tpus < 0:
        # Auto-detect TPU hardware (reference TPUAcceleratorManager
        # detection, tpu.py:47-118): contributes TPU chips, the
        # accelerator_type marker, the per-slice TPU-<type>-head resource
        # (exactly one coordination actor per slice), and the ICI
        # topology labels the slice-aware PACK/label policies consume.
        try:
            from ray_tpu._private.accelerators.tpu import \
                TPUAcceleratorManager

            resources.update(TPUAcceleratorManager.node_resources())
            acc = TPUAcceleratorManager.accelerator_type()
            pod = TPUAcceleratorManager.pod_name()
            if pod:
                labels.setdefault("tpu-slice", pod)
            if acc:
                labels.setdefault("tpu-pod-type", acc)
        except Exception:  # noqa: BLE001 — no TPU on this host
            logger.exception(
                "TPU auto-detection failed; registering without TPU "
                "resources (pass --num-tpus to set them explicitly)")
    resources.update(json.loads(args.resources))
    nm = NodeManager(args.gcs_address, port=args.port, resources=resources,
                     labels=labels)
    print(f"NODE_PORT={nm.port}", flush=True)
    print(f"NODE_ID={nm.node_id}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        nm.shutdown()


if __name__ == "__main__":  # pragma: no cover
    main()
