"""Remote-driver proxy: the ``ray://`` tier.

Reference: Ray Client (``python/ray/util/client/server/server.py:96``) —
a Python driver OUTSIDE the cluster network connects to ONE proxy
endpoint on the head; the proxy hosts a server-side driver session (a
real in-cluster runtime) and relays the public API over a single framed
TCP connection. Without this tier, ``ray://`` degrades to a direct GCS
connect that requires the driver to reach every node's object/worker
ports.

Protocol: one fastpath frame per op; request/reply are cloudpickle
tuples. Ops carry a session id; each session's proxy-held ObjectRefs pin
objects on behalf of the remote driver and are dropped on ``close`` (or
by the idle reaper when a client vanishes — the client pings from a
daemon thread).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import cloudpickle

from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime.interface import CoreRuntime

logger = logging.getLogger(__name__)

KIND_CLIENT = 24
SESSION_IDLE_TTL_S = 120.0
PING_PERIOD_S = 20.0


def _session_ttl_s() -> float:
    """Idle TTL after which a silent client's session is reaped (its refs
    released, pinned objects freed). Env-overridable so crash-path tests
    don't wait two minutes for the sweep."""
    try:
        return float(os.environ.get("RAY_TPU_CLIENT_SESSION_TTL_S",
                                    SESSION_IDLE_TTL_S))
    except ValueError:
        return SESSION_IDLE_TTL_S


class ClientProxyServer:
    """Head-side proxy hosting driver sessions for remote clients."""

    def __init__(self, address: str, host: str = "127.0.0.1",
                 port: int = 0, namespace: str = "default"):
        from ray_tpu._private import fastpath
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.runtime.cluster import ClusterRuntime

        # ONE in-cluster runtime shared by every session; per-session ref
        # registries provide isolation of object lifetimes. The runtime
        # must be THE process's global worker: ObjectRef refcount hooks
        # route through it, and without registration the session "pins"
        # would be inert (no release on close, no GCS holder accounting,
        # unbounded memory-store growth).
        w = worker_mod.global_worker_or_none()
        if w is not None:
            if not isinstance(w.core, ClusterRuntime):
                raise RuntimeError(
                    "ClientProxyServer needs a cluster connection, but "
                    "this process already runs a non-cluster runtime")
            self._runtime = w.core
        else:
            self._runtime = ClusterRuntime.connect(address,
                                                   namespace=namespace)
            worker_mod._global_worker = worker_mod.Worker(
                self._runtime, "driver", namespace)
        self._sessions: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._server = fastpath.FastServer(self._handle, host=host,
                                           port=port)
        self.address = self._server.address
        self.port = self._server.port
        self._owns_runtime = w is None
        self._stop = threading.Event()
        threading.Thread(target=self._reaper_loop, daemon=True,
                         name="client-proxy-reaper").start()

    # ----------------------------------------------------------- sessions
    def _session(self, sid: str) -> Dict[str, Any]:
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                s = self._sessions[sid] = {"refs": {}, "actors": {},
                                           "last": 0.0}
            s["last"] = time.monotonic()
            return s

    def _drop_session(self, sid: str) -> None:
        with self._lock:
            s = self._sessions.pop(sid, None)
        if not s:
            return
        s["refs"].clear()  # ObjectRef __del__ releases the pins
        # Non-detached actors belong to the (now gone) remote driver:
        # without this they outlive the session forever, since the
        # proxy-side runtime that nominally owns them never exits.
        for aid_bin, detached in s["actors"].items():
            if detached:
                continue
            try:
                self._runtime.kill_actor(ActorID(aid_bin),
                                         no_restart=True)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        s["actors"].clear()

    def _reaper_loop(self) -> None:
        ttl = _session_ttl_s()
        period = min(10.0, max(ttl / 4.0, 0.25))
        while not self._stop.wait(period):
            cutoff = time.monotonic() - ttl
            with self._lock:
                dead = [sid for sid, s in self._sessions.items()
                        if s["last"] < cutoff]
            for sid in dead:
                logger.info("reaping idle client session %s", sid[:8])
                self._drop_session(sid)

    # ------------------------------------------------------------ serving
    def _handle(self, kind: int, payload: bytes) -> bytes:
        if kind != KIND_CLIENT:
            raise ValueError(f"unknown frame kind {kind}")
        op, sid, args = cloudpickle.loads(payload)
        try:
            if op == "close":
                self._drop_session(sid)
                return cloudpickle.dumps(("ok", True))
            out = getattr(self, f"_op_{op}")(self._session(sid), *args)
            return cloudpickle.dumps(("ok", out))
        except BaseException as e:  # noqa: BLE001 — relayed to the client
            try:
                return cloudpickle.dumps(("err", e))
            except Exception:  # unpicklable exception chain
                return cloudpickle.dumps(("err", RuntimeError(repr(e))))

    def _hold(self, session, refs: Sequence[ObjectRef]) -> None:
        for r in refs:
            session["refs"][r.id().binary()] = r

    def _ref_of(self, session, oid_bin: bytes) -> ObjectRef:
        ref = session["refs"].get(oid_bin)
        if ref is not None:
            return ref
        return ObjectRef(ObjectID(oid_bin), skip_ref_count=True)

    # ---------------------------------------------------------------- ops
    def _op_ping(self, session):
        # The reply carries the PROXY-side session TTL so clients pace
        # keep-alives off the authoritative value — a TTL shortened only
        # on the head must not let it reap live-but-idle clients.
        return {"ttl_s": _session_ttl_s()}

    def _op_put(self, session, blob: bytes):
        value = cloudpickle.loads(blob)
        ref = self._runtime.put(value)
        self._hold(session, [ref])
        return ref.id().binary()

    def _op_get(self, session, oid_bins: List[bytes],
                timeout: Optional[float]):
        refs = [self._ref_of(session, ob) for ob in oid_bins]
        return cloudpickle.dumps(self._runtime.get(refs, timeout))

    def _op_wait(self, session, oid_bins, num_returns, timeout, fetch_local):
        refs = [self._ref_of(session, ob) for ob in oid_bins]
        ready, not_ready = self._runtime.wait(refs, num_returns, timeout,
                                              fetch_local)
        return ([r.id().binary() for r in ready],
                [r.id().binary() for r in not_ready])

    def _op_submit_task(self, session, blob: bytes):
        function, function_name, args, kwargs, options = \
            cloudpickle.loads(blob)
        refs = self._runtime.submit_task(function, function_name, args,
                                         kwargs, options)
        self._hold(session, refs)
        return [r.id().binary() for r in refs]

    def _op_create_actor(self, session, blob: bytes):
        cls, args, kwargs, options = cloudpickle.loads(blob)
        actor_id = self._runtime.create_actor(cls, args, kwargs, options)
        detached = getattr(options, "lifetime", None) == "detached"
        session["actors"][actor_id.binary()] = detached
        return actor_id.binary()

    def _op_submit_actor_task(self, session, actor_id_bin, method_name,
                              blob, options_blob):
        args, kwargs = cloudpickle.loads(blob)
        options = cloudpickle.loads(options_blob)
        refs = self._runtime.submit_actor_task(
            ActorID(actor_id_bin), method_name, args, kwargs, options)
        self._hold(session, refs)
        return [r.id().binary() for r in refs]

    def _op_kill_actor(self, session, actor_id_bin, no_restart):
        session["actors"].pop(actor_id_bin, None)
        return self._runtime.kill_actor(ActorID(actor_id_bin), no_restart)

    def _op_get_named_actor(self, session, name, namespace):
        actor_id, cls, options = self._runtime.get_named_actor(name,
                                                               namespace)
        return cloudpickle.dumps((actor_id.binary(), cls, options))

    def _op_list_named_actors(self, session, all_namespaces):
        return self._runtime.list_named_actors(all_namespaces)

    def _op_cancel(self, session, oid_bin, force, recursive):
        return self._runtime.cancel(self._ref_of(session, oid_bin), force,
                                    recursive)

    def _op_free(self, session, oid_bins):
        return self._runtime.free(
            [self._ref_of(session, ob) for ob in oid_bins])

    def _op_del_refs(self, session, oid_bins):
        for ob in oid_bins:
            session["refs"].pop(ob, None)
        return True

    def _op_nodes(self, session):
        return self._runtime.nodes()

    def _op_cluster_resources(self, session):
        return self._runtime.cluster_resources()

    def _op_available_resources(self, session):
        return self._runtime.available_resources()

    def close(self) -> None:
        self._stop.set()
        self._server.close()
        if self._owns_runtime:
            # Only shut down a runtime this proxy created — when embedded
            # in a driver process, the host's runtime outlives the proxy.
            try:
                self._runtime.shutdown()
            except Exception:  # noqa: BLE001
                pass


class ProxyRuntime(CoreRuntime):
    """Client-side runtime: the full public API relayed through ONE
    proxy connection — the driver needs no reachability to the GCS,
    node managers, or workers (reference: the Ray Client API surface,
    ``util/client/api.py``)."""

    def __init__(self, proxy_address: str, namespace: str = "default"):
        from ray_tpu._private import fastpath

        self._address = proxy_address
        self._fc = fastpath.get_client(proxy_address)
        if self._fc is None:
            raise ConnectionError(
                f"cannot reach ray:// proxy at {proxy_address}")
        self._sid = uuid.uuid4().hex
        self._counts: Dict[bytes, int] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._session_lost = False
        self.node_id = f"client-{self._sid[:8]}"
        self.job_id = self.node_id
        # The proxy's shared runtime has ONE namespace; this client's
        # namespace rides explicitly on named-actor ops instead.
        self.namespace = namespace
        # Bounded handshake: a wrong-but-listening endpoint must fail
        # init() in seconds, not hang on the data-op timeout.
        self._server_ttl_s = None
        try:
            hello = self._call("ping", _timeout=10.0)
            if isinstance(hello, dict):
                self._server_ttl_s = hello.get("ttl_s")
        except Exception as e:
            raise ConnectionError(
                f"ray:// endpoint {proxy_address} did not answer the "
                f"proxy handshake — is the client proxy running there? "
                f"(python -m ray_tpu._private.client_proxy)") from e
        threading.Thread(target=self._ping_loop, daemon=True,
                         name="client-proxy-ping").start()

    # ------------------------------------------------------------ plumbing
    def _call(self, op: str, *args, _timeout: float = 24 * 3600.0):
        if self._session_lost and op not in ("close",):
            raise ConnectionError(
                "ray:// session lost: the proxy was unreachable for "
                "longer than the session TTL, so the server-side "
                "session (and every object/actor it pinned) has been "
                "reaped — reconnect with a fresh ray_tpu.init()")
        data = self._fc.call(
            KIND_CLIENT, cloudpickle.dumps((op, self._sid, args)),
            timeout=_timeout)
        status, out = cloudpickle.loads(data)
        if status == "err":
            raise out
        return out

    def _ping_loop(self):
        # Ping faster than the server reaps, or a live-but-idle client
        # would be swept between keep-alives. The TTL comes from the
        # proxy's handshake reply (authoritative — the env knob may be
        # set only on the head), falling back to this process's env.
        # A FAILED ping must not end the loop (one dropped frame or a
        # proxy restart used to kill keep-alives permanently, so the
        # proxy reaped a perfectly live client minutes later): retry
        # with backoff, and only once the outage outlasts the TTL flag
        # the session lost so the next op fails with a clear error
        # instead of silently acting on a reaped (auto-recreated,
        # empty) server-side session.
        ttl = self._server_ttl_s or _session_ttl_s()
        period = min(PING_PERIOD_S, max(ttl / 3.0, 0.2))
        last_ok = time.monotonic()
        failures = 0
        while not self._closed:
            time.sleep(period if failures == 0
                       else min(period, 0.25 * (2 ** min(failures, 4))))
            if self._closed:
                return
            try:
                self._call("ping")
                failures = 0
                last_ok = time.monotonic()
            except Exception:  # noqa: BLE001 — proxy briefly unreachable
                if self._session_lost:
                    return
                failures += 1
                if time.monotonic() - last_ok > ttl:
                    self._session_lost = True
                    logger.warning(
                        "ray:// proxy unreachable for %.0fs (> session "
                        "TTL %.0fs); session %s is lost",
                        time.monotonic() - last_ok, ttl, self._sid[:8])
                    return

    def _make_refs(self, oid_bins: List[bytes]) -> List[ObjectRef]:
        return [ObjectRef(ObjectID(ob), owner_address=self._address)
                for ob in oid_bins]

    # ---------------------------------------------------------------- api
    def put(self, value: Any, owner_ref: Optional[ObjectRef] = None
            ) -> ObjectRef:
        oid_bin = self._call("put", cloudpickle.dumps(value))
        return self._make_refs([oid_bin])[0]

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]
            ) -> List[Any]:
        # Errors are OUT of band: the server-side get raises and _call
        # re-raises the relayed exception, typed.
        blob = self._call("get", [r.id().binary() for r in refs], timeout)
        return cloudpickle.loads(blob)

    def wait(self, refs, num_returns, timeout, fetch_local):
        by_id = {r.id().binary(): r for r in refs}
        ready_b, not_b = self._call(
            "wait", list(by_id), num_returns, timeout, fetch_local)
        return ([by_id[b] for b in ready_b], [by_id[b] for b in not_b])

    def free(self, refs) -> None:
        self._call("free", [r.id().binary() for r in refs])

    def submit_task(self, function, function_name, args, kwargs, options):
        oid_bins = self._call("submit_task", cloudpickle.dumps(
            (function, function_name, args, kwargs, options)))
        return self._make_refs(oid_bins)

    def cancel(self, ref, force, recursive) -> None:
        self._call("cancel", ref.id().binary(), force, recursive)

    def create_actor(self, cls, args, kwargs, options) -> ActorID:
        import dataclasses

        if getattr(options, "namespace", None) is None:
            options = dataclasses.replace(options,
                                          namespace=self.namespace)
        return ActorID(self._call("create_actor", cloudpickle.dumps(
            (cls, args, kwargs, options))))

    def submit_actor_task(self, actor_id, method_name, args, kwargs,
                          options):
        oid_bins = self._call(
            "submit_actor_task", actor_id.binary(), method_name,
            cloudpickle.dumps((args, kwargs)), cloudpickle.dumps(options))
        return self._make_refs(oid_bins)

    def kill_actor(self, actor_id, no_restart) -> None:
        self._call("kill_actor", actor_id.binary(), no_restart)

    def get_named_actor(self, name, namespace):
        actor_id_bin, cls, options = cloudpickle.loads(
            self._call("get_named_actor", name,
                       namespace or self.namespace))
        return ActorID(actor_id_bin), cls, options

    def list_named_actors(self, all_namespaces):
        return self._call("list_named_actors", all_namespaces)

    # ------------------------------------------------------- ref counting
    def add_local_reference(self, ref: ObjectRef) -> None:
        with self._lock:
            ob = ref.id().binary()
            self._counts[ob] = self._counts.get(ob, 0) + 1

    def remove_local_reference(self, object_id: ObjectID) -> None:
        release = False
        with self._lock:
            ob = object_id.binary()
            n = self._counts.get(ob, 0) - 1
            if n <= 0:
                self._counts.pop(ob, None)
                release = True
            else:
                self._counts[ob] = n
        if release and not self._closed:
            try:
                self._call("del_refs", [ob])
            except Exception:  # noqa: BLE001 — teardown race
                pass

    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def poll():
            try:
                fut.set_result(self.get([ref], None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=poll, daemon=True).start()
        return fut

    # ------------------------------------------------------------- cluster
    def nodes(self):
        return self._call("nodes")

    def cluster_resources(self):
        return self._call("cluster_resources")

    def available_resources(self):
        return self._call("available_resources")

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._call("close")
        except Exception:  # noqa: BLE001
            pass
        try:
            self._fc.shutdown()
        except Exception:  # noqa: BLE001
            pass


def main(argv=None):  # pragma: no cover — subprocess entry
    import argparse

    parser = argparse.ArgumentParser(description="ray:// driver proxy")
    parser.add_argument("--address", required=True,
                        help="cluster GCS/head address")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=10001)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = ClientProxyServer(args.address, host=args.host,
                               port=args.port)
    print(f"CLIENT_PROXY_PORT={server.port}", flush=True)
    print(f"CLIENT_PROXY_ADDRESS={server.address}", flush=True)
    threading.Event().wait()


if __name__ == "__main__":  # pragma: no cover
    main()
