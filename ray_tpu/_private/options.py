"""Task / actor submission options.

Re-design of the reference options plumbing (reference:
``python/ray/_private/ray_option_utils.py``): a validated dataclass shared by
``@remote`` decorators and ``.options(...)`` overrides.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


def is_streaming(num_returns: Any) -> bool:
    """True when ``num_returns`` requests a streaming generator task
    (``"streaming"``, or the reference's ``"dynamic"`` alias)."""
    return num_returns in ("streaming", "dynamic")


@dataclasses.dataclass
class RemoteOptions:
    # Resources. ``num_tpus`` is first-class: a task/actor holding N tpu chips
    # gets TPU_VISIBLE_CHIPS set for its process (reference analog:
    # num_gpus + CUDA_VISIBLE_DEVICES in worker.py:991).
    num_cpus: Optional[float] = None
    num_gpus: Optional[float] = None
    num_tpus: Optional[float] = None
    memory: Optional[float] = None
    resources: Optional[Dict[str, float]] = None

    # Task behavior. num_returns: int, or "streaming"/"dynamic" for
    # generator tasks whose yields become an ObjectRefGenerator.
    num_returns: Any = 1
    max_retries: Optional[int] = None
    retry_exceptions: Any = False  # False | True | list of exception types
    name: Optional[str] = None

    # Actor behavior.
    max_restarts: int = 0
    max_task_retries: int = 0
    # None = unset: sync actors run ordered (1); async actors default to
    # 1000 concurrent awaits. An EXPLICIT 1 stays 1 even on async actors
    # (e.g. a serve deployment with max_ongoing_requests=1 must serialize).
    max_concurrency: Optional[int] = None
    max_pending_calls: int = -1
    lifetime: Optional[str] = None  # None | "detached"
    namespace: Optional[str] = None
    get_if_exists: bool = False

    # Placement.
    scheduling_strategy: Any = None  # None|"DEFAULT"|"SPREAD"|strategy object
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    # Environment.
    runtime_env: Optional[Dict[str, Any]] = None

    # Concurrency groups for actors: {"group": max_concurrency}.
    concurrency_groups: Optional[Dict[str, int]] = None

    # Internal.
    _is_actor: bool = False
    # Set at ActorClass._remote time (the client sees the class): async
    # actors get a wider submitter send window so max_concurrency isn't
    # silently capped by the in-flight push limit.
    _is_async_actor: bool = False

    def merged_with(self, overrides: Dict[str, Any]) -> "RemoteOptions":
        known = {f.name for f in dataclasses.fields(self)}
        bad = set(overrides) - known
        if bad:
            raise ValueError(f"Unknown options: {sorted(bad)}")
        return dataclasses.replace(self, **overrides)

    def task_resources(self, default_num_cpus: float = 1.0) -> Dict[str, float]:
        """Resolve the resource demand of one invocation."""
        req: Dict[str, float] = {}
        cpus = self.num_cpus
        if cpus is None:
            cpus = 0.0 if self._is_actor else default_num_cpus
        if cpus:
            req["CPU"] = float(cpus)
        if self.num_gpus:
            req["GPU"] = float(self.num_gpus)
        if self.num_tpus:
            req["TPU"] = float(self.num_tpus)
        if self.memory:
            req["memory"] = float(self.memory)
        for k, v in (self.resources or {}).items():
            if k in ("CPU", "GPU", "TPU"):
                raise ValueError(
                    f"Use num_cpus/num_gpus/num_tpus instead of resources[{k!r}]"
                )
            req[k] = float(v)
        return req


def options_from_decorator_kwargs(kwargs: Dict[str, Any], is_actor: bool) -> RemoteOptions:
    opts = RemoteOptions(_is_actor=is_actor)
    return opts.merged_with(kwargs)


@dataclasses.dataclass
class PlacementFields:
    """Resolved scheduling-strategy fields, 1:1 with the TaskSpec proto
    (reference: TaskSpecification scheduling_strategy,
    ``common/task/task_spec.h`` + ``scheduling_strategies.py``)."""

    placement_group_id: bytes = b""
    bundle_index: int = -1
    capture_child_tasks: bool = False
    affinity_node_id: str = ""
    affinity_soft: bool = False
    strategy: str = ""  # "" | "DEFAULT" | "SPREAD"
    label_selector: bytes = b""  # JSON, NodeLabelSchedulingStrategy.encode()


def resolve_placement(options: RemoteOptions) -> PlacementFields:
    """Collapse ``scheduling_strategy`` / ``placement_group=`` options (and,
    absent both, the worker's capture context) into TaskSpec fields.

    Matches reference precedence: an explicit strategy object wins, then the
    legacy ``placement_group=`` option, then
    ``placement_group_capture_child_tasks`` inherited from the running task.
    """
    out = PlacementFields()
    strat = options.scheduling_strategy
    pg = options.placement_group
    idx = options.placement_group_bundle_index
    capture = options.placement_group_capture_child_tasks
    if strat is not None:
        if isinstance(strat, str):
            if strat not in ("DEFAULT", "SPREAD"):
                raise ValueError(
                    f"Unknown scheduling strategy {strat!r}; expected "
                    "'DEFAULT', 'SPREAD', or a strategy object")
            out.strategy = strat
        elif hasattr(strat, "placement_group"):
            pg = strat.placement_group
            idx = strat.placement_group_bundle_index
            capture = strat.placement_group_capture_child_tasks
        elif hasattr(strat, "node_id"):
            out.affinity_node_id = strat.node_id
            out.affinity_soft = bool(strat.soft)
            return out
        elif hasattr(strat, "hard") and hasattr(strat, "encode"):
            out.label_selector = strat.encode()
            return out
        else:
            raise ValueError(f"Unknown scheduling strategy {strat!r}")
    if pg is not None:
        group_id = pg.id if hasattr(pg, "id") else pg
        if idx >= len(getattr(pg, "bundle_specs", [])) and \
                getattr(pg, "bundle_specs", None):
            raise ValueError(
                f"placement_group_bundle_index {idx} out of range for a "
                f"group with {len(pg.bundle_specs)} bundles")
        out.placement_group_id = group_id
        out.bundle_index = idx
        out.capture_child_tasks = bool(capture)
        return out
    if not out.strategy:
        # Inherit the capturing group of the currently-executing task.
        from ray_tpu._private import pg_context

        ctx = pg_context.get()
        if ctx is not None:
            gid, _bidx, cap = ctx
            if cap:
                out.placement_group_id = gid
                out.bundle_index = -1
                out.capture_child_tasks = True
    return out
