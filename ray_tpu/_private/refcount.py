"""Distributed reference counting for cluster objects.

Re-design of the reference's ownership/refcount protocol (reference:
``src/ray/core_worker/reference_count.h:66``) for a GCS-centric control
plane: instead of peer-to-peer borrowing messages between owner workers, each
process keeps exact local counts of live ``ObjectRef`` instances and flushes
*deltas* to the GCS in the background. The GCS sums counts across holders and,
when an object's total drops to zero, frees every stored copy and clears the
directory entry (the owner also drops its pinned lineage — see
``ClusterRuntime``). Borrowing falls out naturally: deserializing a ref in a
worker registers a +1 from that holder; the submitting process pins task-arg
refs for the duration of the task so the count can never dip to zero between
submit and the worker's borrow registration.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)

FLUSH_PERIOD_S = 0.1


class ReferenceCounter:
    """Per-process local refcounts with batched delta flush to the GCS.

    ``on_local_zero(oid_binary)`` fires when this process's count for an
    object reaches zero (used to evict the in-process memory store and drop
    pinned lineage).
    """

    def __init__(self, gcs_stub, holder_id: str,
                 on_local_zero: Optional[Callable[[bytes], None]] = None):
        self._gcs = gcs_stub
        self._holder = holder_id
        self._on_local_zero = on_local_zero
        self._counts: Dict[bytes, int] = {}
        self._pending: Dict[bytes, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="refcount-flush")
        self._thread.start()

    # ------------------------------------------------------------------ api
    def incr(self, oid: bytes, n: int = 1) -> None:
        with self._lock:
            self._counts[oid] = self._counts.get(oid, 0) + n
            self._pending[oid] = self._pending.get(oid, 0) + n

    def decr(self, oid: bytes, n: int = 1) -> None:
        zero = False
        with self._lock:
            cur = self._counts.get(oid, 0) - n
            if cur <= 0:
                self._counts.pop(oid, None)
                zero = cur == 0
            else:
                self._counts[oid] = cur
            self._pending[oid] = self._pending.get(oid, 0) - n
        if zero and self._on_local_zero is not None:
            try:
                self._on_local_zero(oid)
            except Exception:  # noqa: BLE001
                logger.exception("on_local_zero failed for %s", oid.hex()[:12])

    def local_count(self, oid: bytes) -> int:
        with self._lock:
            return self._counts.get(oid, 0)

    def flush(self) -> None:
        with self._lock:
            deltas = {k: v for k, v in self._pending.items() if v != 0}
            # A net-zero pending entry whose local count is also zero means
            # the object was created AND fully dropped within one flush
            # window; the GCS never saw it, so stored copies would leak.
            # Emit an explicit +1/-1 pair to drive the GCS free path.
            transient = [k for k, v in self._pending.items()
                         if v == 0 and self._counts.get(k, 0) == 0]
            self._pending.clear()
        if not deltas and not transient:
            return
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        req = pb.UpdateRefCountsRequest(holder_id=self._holder)
        for oid, delta in deltas.items():
            req.deltas.append(pb.RefCountDelta(object_id=oid, delta=delta))
        for oid in transient:
            req.deltas.append(pb.RefCountDelta(object_id=oid, delta=1))
            req.deltas.append(pb.RefCountDelta(object_id=oid, delta=-1))
        try:
            self._gcs.UpdateRefCounts(req, timeout=5)
        except Exception:  # noqa: BLE001 — GCS down: re-queue for next flush
            with self._lock:
                for oid, delta in deltas.items():
                    self._pending[oid] = self._pending.get(oid, 0) + delta

    def _flush_loop(self) -> None:
        while not self._stop.wait(FLUSH_PERIOD_S):
            self.flush()

    def shutdown(self) -> None:
        """Release every count this process still holds and stop flushing."""
        self._stop.set()
        with self._lock:
            for oid, n in self._counts.items():
                self._pending[oid] = self._pending.get(oid, 0) - n
            self._counts.clear()
        self.flush()
