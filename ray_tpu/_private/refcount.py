"""Distributed reference counting for cluster objects.

Re-design of the reference's ownership/refcount protocol (reference:
``src/ray/core_worker/reference_count.h:66``) for a GCS-centric control
plane: instead of peer-to-peer borrowing messages between owner workers, each
process keeps exact local counts of live ``ObjectRef`` instances and flushes
*deltas* to the GCS in the background. The GCS sums counts across holders and,
when an object's total drops to zero, frees every stored copy and clears the
directory entry (the owner also drops its pinned lineage — see
``ClusterRuntime``).

Zero-dip safety is ordering-based, not time-based: the submitting process pins
every ref contained in a task's payload until the push RPC returns, and the
executing worker *synchronously* flushes its borrow (+1) before running user
code — so the GCS observes the worker's +1 strictly before the submitter's
pin release. The GCS's short free-grace timer remains only as defense in
depth for refs handed off outside the task-arg path (e.g. refs embedded in
``put()`` values read by a process that holds no other count).

Holder liveness (reference ties refs to owner liveness): every flush carries
the holder's node id; worker holders are reaped by the GCS on node death and
by the node manager on worker-process death (``ReapHolder``). Driver holders
(which survive node failover) are reaped by a flush-ping TTL — the counter
sends an empty flush at least every ``PING_PERIOD_S`` while it holds counts.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Set

logger = logging.getLogger(__name__)

FLUSH_PERIOD_S = 0.1
PING_PERIOD_S = 2.0


class ReferenceCounter:
    """Per-process local refcounts with batched delta flush to the GCS.

    ``on_local_zero(oid_binary)`` fires when this process's count for an
    object reaches zero (used to evict the in-process memory store and drop
    pinned lineage).
    """

    def __init__(self, gcs_stub, holder_id: str,
                 on_local_zero: Optional[Callable[[bytes], None]] = None,
                 node_id: str = "", is_driver: bool = True):
        self._gcs = gcs_stub
        self._holder = holder_id
        self._node_id = node_id
        self._is_driver = is_driver
        self._on_local_zero = on_local_zero
        self._counts: Dict[bytes, int] = {}
        self._pending: Dict[bytes, int] = {}
        # Transient +1/-1 pairs that failed to reach the GCS (ADVICE r2 #4):
        # without re-emission their stored copies would leak forever.
        self._transient_retry: Set[bytes] = set()
        self._lock = threading.Lock()
        self._last_flush = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="refcount-flush")
        self._thread.start()

    # ------------------------------------------------------------------ api
    def incr(self, oid: bytes, n: int = 1) -> None:
        with self._lock:
            self._counts[oid] = self._counts.get(oid, 0) + n
            self._pending[oid] = self._pending.get(oid, 0) + n

    def decr(self, oid: bytes, n: int = 1) -> None:
        zero = False
        with self._lock:
            cur = self._counts.get(oid, 0) - n
            if cur <= 0:
                self._counts.pop(oid, None)
                zero = cur == 0
            else:
                self._counts[oid] = cur
            self._pending[oid] = self._pending.get(oid, 0) - n
        if zero and self._on_local_zero is not None:
            try:
                self._on_local_zero(oid)
            except Exception:  # noqa: BLE001
                logger.exception("on_local_zero failed for %s", oid.hex()[:12])

    def local_count(self, oid: bytes) -> int:
        with self._lock:
            return self._counts.get(oid, 0)

    def flush(self, force_ping: bool = False) -> bool:
        """Push pending deltas to the GCS. Returns True on success (or when
        there was nothing to send and no ping was due)."""
        with self._lock:
            deltas = {k: v for k, v in self._pending.items() if v != 0}
            # A net-zero pending entry whose local count is also zero means
            # the object was created AND fully dropped within one flush
            # window; the GCS never saw it, so stored copies would leak.
            # Emit an explicit +1/-1 pair to drive the GCS free path.
            transient = set(
                k for k, v in self._pending.items()
                if v == 0 and self._counts.get(k, 0) == 0)
            transient |= self._transient_retry
            transient -= set(deltas)
            self._transient_retry = set()
            self._pending.clear()
            holding = bool(self._counts)
        ping_due = holding and (
            force_ping
            or time.monotonic() - self._last_flush >= PING_PERIOD_S)
        if not deltas and not transient and not ping_due:
            return True
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        req = pb.UpdateRefCountsRequest(
            holder_id=self._holder, node_id=self._node_id,
            is_driver=self._is_driver)
        for oid, delta in deltas.items():
            req.deltas.append(pb.RefCountDelta(object_id=oid, delta=delta))
        for oid in transient:
            req.deltas.append(pb.RefCountDelta(object_id=oid, delta=1))
            req.deltas.append(pb.RefCountDelta(object_id=oid, delta=-1))
        try:
            self._gcs.UpdateRefCounts(req, timeout=5)
            self._last_flush = time.monotonic()
            return True
        except Exception:  # noqa: BLE001 — GCS down: re-queue for next flush
            with self._lock:
                for oid, delta in deltas.items():
                    self._pending[oid] = self._pending.get(oid, 0) + delta
                self._transient_retry |= transient
            return False

    def _flush_loop(self) -> None:
        while not self._stop.wait(FLUSH_PERIOD_S):
            self.flush()

    def shutdown(self) -> None:
        """Release every count this process still holds and stop flushing."""
        self._stop.set()
        with self._lock:
            for oid, n in self._counts.items():
                self._pending[oid] = self._pending.get(oid, 0) - n
            self._counts.clear()
        self.flush()
