"""Cluster scheduling policies.

Reference: ``src/ray/raylet/scheduling/policy/`` (SURVEY.md C16) — hybrid
(pack-until-50%-then-spread, ``hybrid_scheduling_policy.cc:99,186``), spread,
node-affinity, and the bundle policies for placement groups
(``bundle_scheduling_policy.h``). TPU-native addition: nodes carry topology
labels (``tpu-slice``, ``tpu-pod-type``) and bundle PACK prefers keeping a
group inside one ICI-connected slice — the property that decides whether
collectives ride ICI or DCN.
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

from ray_tpu.protobuf import ray_tpu_pb2 as pb

HYBRID_THRESHOLD = 0.5  # reference: RAY_scheduler_spread_threshold default


def _fits(node: pb.NodeInfo, demand: Dict[str, float]) -> bool:
    return all(node.available.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _feasible(node: pb.NodeInfo, demand: Dict[str, float]) -> bool:
    return all(node.resources.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _utilization(node: pb.NodeInfo) -> float:
    """Critical-resource utilization in [0, 1]."""
    utils = []
    for k, total in node.resources.items():
        if total <= 0:
            continue
        utils.append(1.0 - node.available.get(k, 0.0) / total)
    return max(utils) if utils else 0.0


def pick_node_hybrid(
    nodes: Sequence[pb.NodeInfo],
    demand: Dict[str, float],
    local_node_id: Optional[str] = None,
    spread_threshold: float = HYBRID_THRESHOLD,
) -> Optional[str]:
    """Default policy: prefer packing onto low-index (local-first) nodes while
    their utilization stays under the threshold, then spread by lowest
    utilization (reference: hybrid_scheduling_policy.cc:99)."""
    alive = [n for n in nodes if n.alive and _fits(n, demand)]
    if not alive:
        return None
    # local-first ordering, then stable by node id for determinism
    alive.sort(key=lambda n: (n.node_id != local_node_id, n.node_id))
    below = [n for n in alive if _utilization(n) < spread_threshold]
    if below:
        return below[0].node_id
    return min(alive, key=_utilization).node_id


def util_after(node: pb.NodeInfo, demand: Dict[str, float]) -> float:
    """Critical-resource utilization AFTER charging ``demand`` — the
    quantity SPREAD placement must compare (pre-charge utilization lets an
    idle-but-small node swallow a whole fan-out serially)."""
    utils = []
    for k, total in node.resources.items():
        if total <= 0:
            continue
        used = total - node.available.get(k, 0.0) + demand.get(k, 0.0)
        utils.append(used / total)
    return max(utils) if utils else 0.0


def pick_node_spread(
    nodes: Sequence[pb.NodeInfo], demand: Dict[str, float]
) -> Optional[str]:
    alive = [n for n in nodes if n.alive and _fits(n, demand)]
    if not alive:
        return None
    # Rank by POST-charge utilization (what the node would look like with
    # this task on it): pre-charge ranking prefers idle-but-tiny nodes
    # that the demand would instantly saturate.
    return min(alive, key=lambda n: util_after(n, demand)).node_id


def pick_node_affinity(
    nodes: Sequence[pb.NodeInfo], demand: Dict[str, float],
    node_id: str, soft: bool,
) -> Optional[str]:
    for n in nodes:
        if n.node_id == node_id and n.alive and _fits(n, demand):
            return n.node_id
    if soft:
        return pick_node_hybrid(nodes, demand)
    return None


def feasible_anywhere(nodes: Sequence[pb.NodeInfo], demand: Dict[str, float]) -> bool:
    return any(_feasible(n, demand) for n in nodes if n.alive)


# ------------------------------------------------------------- node labels

def match_labels(labels: Dict[str, str], selector: Dict[str, dict]) -> bool:
    """Evaluate a hard/soft selector map against one node's labels.

    Value specs (see ``util/scheduling_strategies.py`` In/NotIn/Exists/
    DoesNotExist; reference: node_label_scheduling_policy.h semantics):
    ``{"in": [...]}`` requires presence + membership, ``{"not_in": [...]}``
    passes when absent or not a member, ``{"exists": b}`` checks presence.
    """
    for key, spec in selector.items():
        present = key in labels
        if "in" in spec:
            if not present or labels[key] not in spec["in"]:
                return False
        elif "not_in" in spec:
            if present and labels[key] in spec["not_in"]:
                return False
        elif "exists" in spec:
            if present != bool(spec["exists"]):
                return False
    return True


def parse_label_selector(raw: bytes) -> Optional[Dict[str, dict]]:
    """Decode TaskSpec.label_selector; None when unset."""
    if not raw:
        return None
    import json

    return json.loads(bytes(raw).decode())


def feasible_with_labels(nodes: Sequence[pb.NodeInfo], demand: Dict[str, float],
                         selector: Dict[str, dict]) -> bool:
    hard = selector.get("hard") or {}
    return any(_feasible(n, demand) for n in nodes
               if n.alive and match_labels(dict(n.labels), hard))


# ---------------------------------------------------------------- bundles

def place_bundles(
    info: pb.PlacementGroupInfo, nodes: Sequence[pb.NodeInfo],
    pending: Optional[Sequence] = None,
    occupied: Sequence[str] = (),
) -> Optional[List[str]]:
    """Assign each pending bundle a node id per strategy; None if infeasible
    now.

    PACK/STRICT_PACK prefer one node — and among multi-node fallbacks, nodes
    sharing one ``tpu-slice`` label (ICI-connected) are preferred over
    arbitrary nodes (TPU-topology-aware packing).

    ``pending``/``occupied`` support partial re-placement after a node death
    (reference: gcs_placement_group_manager.cc:585): only ``pending`` bundles
    are assigned; ``occupied`` lists nodes hosting the group's surviving
    bundles — STRICT_SPREAD avoids them, STRICT_PACK requires them.
    """
    bundles = list(pending) if pending is not None else list(info.bundles)
    strategy = info.strategy or "PACK"
    alive = [n for n in nodes if n.alive]
    if not alive:
        return None
    if occupied:
        if strategy == "STRICT_PACK":
            # Survivors fix the node: everything re-placed must join them.
            home = occupied[0]
            node = next((n for n in alive if n.node_id == home), None)
            if node is None or not _all_fit(bundles, [dict(node.available)]):
                return None
            return [home] * len(bundles)
        if strategy == "STRICT_SPREAD":
            alive = [n for n in alive if n.node_id not in set(occupied)]
            if not alive:
                return None

    def bundle_demand(b) -> Dict[str, float]:
        return dict(b.resources)

    if strategy in ("PACK", "STRICT_PACK"):
        # Try single node first.
        for n in sorted(alive, key=_utilization):
            avail = dict(n.available)
            if _all_fit(bundles, [avail]):
                return [n.node_id] * len(bundles)
        if strategy == "STRICT_PACK":
            return None
        # Greedy multi-node pack, grouping nodes by slice label first.
        groups = defaultdict(list)
        for n in alive:
            groups[n.labels.get("tpu-slice", n.node_id)].append(n)
        ordered = sorted(groups.values(), key=len, reverse=True)
        flat: List[pb.NodeInfo] = [n for grp in ordered for n in
                                   sorted(grp, key=_utilization)]
        return _greedy_pack(bundles, flat)

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        assignment: List[str] = []
        used: Dict[str, Dict[str, float]] = {
            n.node_id: dict(n.available) for n in alive}
        node_order = sorted(alive, key=_utilization)
        taken: List[str] = []
        for b in bundles:
            demand = bundle_demand(b)
            placed = None
            for n in node_order:
                if strategy == "STRICT_SPREAD" and n.node_id in taken:
                    continue
                avail = used[n.node_id]
                if all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
                    placed = n.node_id
                    break
            if placed is None:
                return None
            for k, v in demand.items():
                used[placed][k] = used[placed].get(k, 0.0) - v
            taken.append(placed)
            assignment.append(placed)
        return assignment

    raise ValueError(f"unknown placement strategy {strategy!r}")


def _all_fit(bundles, avails: List[Dict[str, float]]) -> bool:
    avail = dict(avails[0])
    for b in bundles:
        for k, v in b.resources.items():
            if avail.get(k, 0.0) + 1e-9 < v:
                return False
            avail[k] = avail.get(k, 0.0) - v
    return True


def _greedy_pack(bundles, nodes: List[pb.NodeInfo]) -> Optional[List[str]]:
    used = {n.node_id: dict(n.available) for n in nodes}
    assignment = []
    for b in bundles:
        placed = None
        for n in nodes:
            avail = used[n.node_id]
            if all(avail.get(k, 0.0) + 1e-9 >= v for k, v in b.resources.items()):
                placed = n.node_id
                break
        if placed is None:
            return None
        for k, v in b.resources.items():
            used[placed][k] = used[placed].get(k, 0.0) - v
        assignment.append(placed)
    return assignment
