"""ObjectRef: a future-like handle to a (possibly remote, possibly pending) object.

Re-design of the reference ObjectRef (reference: ``python/ray/_raylet.pyx``
``ObjectRef``): carries the 28-byte ``ObjectID`` (task lineage + index) and the
owner's address. Refcounting hooks (``_register``/``_release``) notify the
runtime on creation/GC so distributed reference counting can free the value.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID, TaskID


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "_call_site", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = "", call_site: str = "",
                 skip_ref_count: bool = False):
        self._id = object_id
        self._owner_address = owner_address
        self._call_site = call_site
        self._registered = False
        if not skip_ref_count:
            from ray_tpu._private import worker as _worker

            w = _worker.global_worker_or_none()
            if w is not None:
                w.core.add_local_reference(self)
                self._registered = True

    # -- identity ---------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self) -> TaskID:
        return self._id.task_id()

    def owner_address(self) -> str:
        return self._owner_address

    def call_site(self) -> str:
        return self._call_site

    @classmethod
    def from_binary(cls, binary: bytes, owner_address: str = "") -> "ObjectRef":
        return cls(ObjectID(binary), owner_address)

    @classmethod
    def nil(cls) -> "ObjectRef":
        return cls(ObjectID.nil(), skip_ref_count=True)

    # -- semantics --------------------------------------------------------
    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        # Plain pickling (outside the framework serializer) keeps id + owner.
        return (_rebuild_ref, (self._id.binary(), self._owner_address))

    def __del__(self):
        if getattr(self, "_registered", False):
            try:
                from ray_tpu._private import worker as _worker

                w = _worker.global_worker_or_none()
                if w is not None:
                    w.core.remove_local_reference(self._id)
            except Exception:
                pass

    def future(self):
        """Return a concurrent.futures.Future resolving to the object's value."""
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().core.as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _rebuild_ref(binary: bytes, owner_address: str) -> ObjectRef:
    ref = ObjectRef(ObjectID(binary), owner_address)
    return ref
