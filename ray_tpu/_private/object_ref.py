"""ObjectRef: a future-like handle to a (possibly remote, possibly pending) object.

Re-design of the reference ObjectRef (reference: ``python/ray/_raylet.pyx``
``ObjectRef``): carries the 28-byte ``ObjectID`` (task lineage + index) and the
owner's address. Refcounting hooks (``_register``/``_release``) notify the
runtime on creation/GC so distributed reference counting can free the value.
"""

from __future__ import annotations

import time
from typing import Optional

from ray_tpu._private.ids import ObjectID, TaskID

# Index space for streamed generator items: distinct from declared returns
# (0..n-1) and put-scoped ids (2^31 + k).
STREAM_INDEX_BASE = 1 << 30


def drain_stream(gen, task_id: TaskID, put) -> int:
    """Drain a streaming-generator task: each yielded value becomes its own
    store object at the deterministic stream id the consumer's
    ObjectRefGenerator polls; the returned count rides the task's declared
    return (reference: ObjectRefStream, ``task_manager.h:104``). ``put`` is
    the executor's object sink ``(ObjectID, value) -> None``. The single
    implementation keeps the id scheme/count protocol identical across the
    local, async-actor, and cluster-worker executors."""
    i = 0
    for item in gen:
        put(ObjectID.from_task(task_id, STREAM_INDEX_BASE + i), item)
        i += 1
    return i


async def drain_stream_async(agen, task_id: TaskID, put) -> int:
    """Async-generator variant of :func:`drain_stream`."""
    i = 0
    async for item in agen:
        put(ObjectID.from_task(task_id, STREAM_INDEX_BASE + i), item)
        i += 1
    return i


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "_call_site", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = "", call_site: str = "",
                 skip_ref_count: bool = False):
        self._id = object_id
        self._owner_address = owner_address
        self._call_site = call_site
        self._registered = False
        if not skip_ref_count:
            from ray_tpu._private import worker as _worker

            w = _worker.global_worker_or_none()
            if w is not None:
                w.core.add_local_reference(self)
                self._registered = True

    # -- identity ---------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self) -> TaskID:
        return self._id.task_id()

    def owner_address(self) -> str:
        return self._owner_address

    def call_site(self) -> str:
        return self._call_site

    @classmethod
    def from_binary(cls, binary: bytes, owner_address: str = "") -> "ObjectRef":
        return cls(ObjectID(binary), owner_address)

    @classmethod
    def nil(cls) -> "ObjectRef":
        return cls(ObjectID.nil(), skip_ref_count=True)

    # -- semantics --------------------------------------------------------
    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        # Plain pickling (outside the framework serializer) keeps id + owner.
        # Serialization IS escape: if this process holds the object's bytes
        # lazily (inline task result not yet flushed to the node store),
        # flush now — whoever receives this ref resolves it through the
        # directory. Covers every pickle path in one place: task results,
        # stream items, gateway replies, user pickles.
        try:
            from ray_tpu._private import worker as _worker

            w = _worker.global_worker_or_none()
            if w is not None:
                hook = getattr(w.core, "_flush_escaped", None)
                if hook is not None:
                    hook((self._id.binary(),))
        except Exception:  # noqa: BLE001 — escape flush is best-effort
            pass
        return (_rebuild_ref, (self._id.binary(), self._owner_address))

    def __del__(self):
        if getattr(self, "_registered", False):
            try:
                from ray_tpu._private import worker as _worker

                w = _worker.global_worker_or_none()
                if w is not None:
                    w.core.remove_local_reference(self._id)
            except Exception:
                pass

    def future(self):
        """Return a concurrent.futures.Future resolving to the object's value."""
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().core.as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _rebuild_ref(binary: bytes, owner_address: str) -> ObjectRef:
    ref = ObjectRef(ObjectID(binary), owner_address)
    return ref


class ObjectRefGenerator:
    """Stream of ObjectRefs from a generator task (reference:
    ``ObjectRefStream``, ``task_manager.h:104`` / ``_raylet.pyx:284``).

    Yields the ref of item *i* as soon as the executor has stored it — the
    task may still be running. Iteration ends when the task finishes and
    ``i`` reaches the item count (carried by the task's declared return).
    ``num_returns="streaming"`` (or ``"dynamic"``) on a generator task
    returns one of these from ``.remote()``.
    """

    def __init__(self, length_ref: ObjectRef, owner_address: str = ""):
        self._length_ref = length_ref
        self._task_id = length_ref.task_id()
        self._owner_address = owner_address
        self._i = 0
        self._length: Optional[int] = None
        self._exhausted = False

    def _check_length(self) -> Optional[int]:
        if self._length is not None:
            return self._length
        from ray_tpu._private import worker as _worker

        core = _worker.global_worker().core
        ready, _ = core.wait([self._length_ref], num_returns=1, timeout=0,
                             fetch_local=True)
        if ready:
            n = core.get([self._length_ref], timeout=30)[0]
            self._length = int(n)
        return self._length

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        # Blocks until the item arrives, the stream ends, or the task's
        # stored error surfaces via the length ref — task failure (incl.
        # worker death) always stores an error there, so no deadline is
        # needed for liveness (reference: generator __next__ blocks).
        return self._next_internal(timeout=None)

    def _next_internal(self, timeout: Optional[float]) -> ObjectRef:
        from ray_tpu import exceptions
        from ray_tpu._private import worker as _worker

        core = _worker.global_worker().core
        oid = ObjectID.from_task(self._task_id, STREAM_INDEX_BASE + self._i)
        ref = ObjectRef(oid, owner_address=self._owner_address)
        deadline = None if timeout is None else time.monotonic() + timeout
        stall_deadline = None
        while True:
            # Item readiness first: items yielded before a mid-stream
            # failure must stay consumable (the length check below raises
            # the task's stored error once we're past the stored items).
            ready, _ = core.wait([ref], num_returns=1, timeout=0.05,
                                 fetch_local=True)
            if ready:
                self._i += 1
                return ref
            n = self._check_length()
            if n is not None and self._i >= n:
                self._exhausted = True
                raise StopIteration
            if n is not None:
                # The count says this item was produced, so a long miss
                # means its copies were lost (e.g. the producing node
                # died). Stream ids carry no lineage of their own; the
                # *length ref* does, and re-executing its task regenerates
                # every item at the same deterministic ids.
                if stall_deadline is None:
                    stall_deadline = time.monotonic() + 10.0
                elif time.monotonic() > stall_deadline:
                    stall_deadline = None
                    rec = getattr(core, "_maybe_reconstruct", None)
                    if rec is None or not rec(self._length_ref):
                        raise exceptions.ObjectLostError(
                            f"streamed item {self._i} of task "
                            f"{self._task_id.hex()[:16]} was lost and "
                            f"cannot be reconstructed")
            if deadline is not None and time.monotonic() > deadline:
                raise exceptions.GetTimeoutError(
                    f"streamed item {self._i} of task "
                    f"{self._task_id.hex()[:16]} did not arrive in "
                    f"{timeout}s")

    def completed(self) -> ObjectRef:
        """Ref resolving when the whole stream has been produced."""
        return self._length_ref

    def __del__(self):
        # Abandoned mid-stream: the tail items have no registered holder,
        # so ask the runtime to reap them once the stream length resolves
        # (reference: ObjectRefStream deletion on generator GC).
        if getattr(self, "_exhausted", True):
            return
        try:
            from ray_tpu._private import worker as _worker

            w = _worker.global_worker_or_none()
            if w is not None:
                reap = getattr(w.core, "release_stream_tail", None)
                if reap is not None:
                    reap(self._length_ref, self._i)
        except Exception:  # noqa: BLE001
            pass

    def __repr__(self):
        return (f"ObjectRefGenerator(task={self._task_id.hex()[:16]}, "
                f"next={self._i})")
