"""In-process object store for small / local objects.

Re-design of the reference ``CoreWorkerMemoryStore`` (reference:
``src/ray/core_worker/store_provider/memory_store/``): a thread-safe map of
``ObjectID -> value`` with blocking waits. Values whose size exceeds the
promotion threshold live in the shared-memory store instead (handled by the
runtime layer); this store only ever sees inline values.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.ids import ObjectID


class _Entry:
    __slots__ = ("value", "ready", "callbacks")

    def __init__(self):
        self.value: Any = None
        self.ready = threading.Event()
        self.callbacks: List[Any] = []


_SENTINEL = object()


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, _Entry] = {}

    def _entry(self, object_id: ObjectID) -> _Entry:
        with self._lock:
            e = self._objects.get(object_id)
            if e is None:
                e = _Entry()
                self._objects[object_id] = e
            return e

    def put(self, object_id: ObjectID, value: Any) -> None:
        e = self._entry(object_id)
        e.value = value
        with self._lock:
            callbacks, e.callbacks = e.callbacks, []
            e.ready.set()
        for cb in callbacks:
            try:
                cb(object_id, e.value)
            except Exception:  # callbacks must not break the putter or peers
                import logging

                logging.getLogger(__name__).exception(
                    "object-ready callback failed for %s", object_id)

    def on_ready(self, object_id: ObjectID, callback) -> None:
        """Invoke ``callback(object_id, value)`` when (or if already) ready."""
        e = self._entry(object_id)
        with self._lock:
            if not e.ready.is_set():
                e.callbacks.append(callback)
                return
        callback(object_id, e.value)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._objects.get(object_id)
        return e is not None and e.ready.is_set()

    def get_if_ready(self, object_id: ObjectID, default=_SENTINEL):
        with self._lock:
            e = self._objects.get(object_id)
        if e is not None and e.ready.is_set():
            return e.value
        if default is _SENTINEL:
            raise KeyError(object_id)
        return default

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        e = self._entry(object_id)
        if not e.ready.wait(timeout):
            from ray_tpu.exceptions import GetTimeoutError

            raise GetTimeoutError(f"Timed out getting object {object_id.hex()}")
        return e.value

    def wait(
        self,
        object_ids: Sequence[ObjectID],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[ObjectID], List[ObjectID]]:
        """Block until ``num_returns`` of ``object_ids`` are ready or timeout.

        Returns (ready, not_ready) preserving input order, like the reference
        ``ray.wait``.
        """
        entries = [self._entry(oid) for oid in object_ids]
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready = [oid for oid, e in zip(object_ids, entries) if e.ready.is_set()]
            if len(ready) >= num_returns:
                ready_list = ready[:num_returns]
                ready_set = set(ready_list)
                not_ready = [oid for oid in object_ids if oid not in ready_set]
                return ready_list, not_ready
            if deadline is not None and time.monotonic() >= deadline:
                ready_set = set(ready)
                return ready, [oid for oid in object_ids if oid not in ready_set]
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            step = 0.002 if remaining is None else min(0.002, remaining)
            # Block on the first non-ready entry with a short timeout so new
            # completions of *any* entry are noticed promptly.
            for e in entries:
                if not e.ready.is_set():
                    e.ready.wait(step)
                    break

    def delete(self, object_ids: Sequence[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                self._objects.pop(oid, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
