"""Content-addressed packaging of directories for runtime environments.

Reference: ``python/ray/_private/runtime_env/packaging.py`` — local
directories become deterministic zips addressed by content hash
(``pkg://<sha256>``), stored in the GCS KV (the reference's internal KV
plays the same role), extracted once per node into a cache directory, and
garbage-collected by an LRU cap on the cache.
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import shutil
import tempfile
import threading
import zipfile
from typing import Iterator, Tuple

from ray_tpu.protobuf import ray_tpu_pb2 as pb

logger = logging.getLogger(__name__)

PKG_PREFIX = "pkg://"
KV_NS = "runtime_env"
# Keep the N most recently used packages per node; older ones are deleted
# (reference: URI reference counting + deletion; an LRU cap is the
# agentless equivalent).
CACHE_CAP = 20
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

_cache_lock = threading.Lock()


def is_uri(s: str) -> bool:
    return isinstance(s, str) and s.startswith(PKG_PREFIX)


def cache_dir() -> str:
    return os.environ.get(
        "RAY_TPU_RUNTIME_ENV_CACHE",
        os.path.join(tempfile.gettempdir(), "ray_tpu_runtime_env_cache"))


def _iter_files(root: str) -> Iterator[Tuple[str, str]]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _EXCLUDE_DIRS)
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            yield full, os.path.relpath(full, root)


def dir_fingerprint(path: str) -> str:
    """Cheap change detector over a directory (relpath + mtime + size per
    file) — used to key the driver's prepared-env cache so edits to a
    working_dir between submissions produce a fresh package instead of a
    stale cache hit. Content hashing happens in :func:`package_directory`;
    this only has to be sensitive, not collision-proof."""
    h = hashlib.sha256()
    for full, rel in _iter_files(path):
        st = os.stat(full)
        h.update(f"{rel}\0{st.st_mtime_ns}\0{st.st_size}\0".encode())
    return h.hexdigest()[:16]


def package_directory(path: str, prefix: str = "") -> Tuple[str, bytes]:
    """Zip ``path`` deterministically. Returns ``(uri, zip_bytes)`` where
    the URI is the sha256 of the content — identical trees share one
    package regardless of where or when they were zipped. ``prefix`` nests
    the tree under one top-level directory (py_modules semantics: the
    packaged directory itself stays importable)."""
    h = hashlib.sha256()
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for full, rel in _iter_files(path):
            arcname = os.path.join(prefix, rel) if prefix else rel
            with open(full, "rb") as f:
                data = f.read()
            h.update(arcname.encode())
            h.update(b"\0")
            h.update(data)
            info = zipfile.ZipInfo(arcname, date_time=(1980, 1, 1, 0, 0, 0))
            info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            zf.writestr(info, data)
    return PKG_PREFIX + h.hexdigest(), buf.getvalue()


def upload_directory(path: str, kv_stub, prefix: str = "") -> str:
    """Package ``path`` and store it in the GCS KV (idempotent: the key is
    the content hash). Returns the ``pkg://`` URI."""
    uri, data = package_directory(path, prefix=prefix)
    kv_stub.KvPut(pb.KvRequest(ns=KV_NS, key=uri, value=data,
                               overwrite=True))
    return uri


def ensure_local(uri: str, kv_stub) -> str:
    """Materialize ``uri`` into this node's cache (download + extract on
    first use) and return the extracted directory path."""
    assert is_uri(uri), uri
    dest = os.path.join(cache_dir(), uri[len(PKG_PREFIX):])
    with _cache_lock:
        if os.path.isdir(dest):
            os.utime(dest)  # LRU touch
            return dest
        reply = kv_stub.KvGet(pb.KvRequest(ns=KV_NS, key=uri))
        if not reply.found:
            raise FileNotFoundError(
                f"runtime_env package {uri} not found in the cluster KV")
        tmp = f"{dest}.tmp.{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(reply.value)) as zf:
            zf.extractall(tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            # Another *process* won the materialization race (the lock
            # above is per-process only); its extraction is equivalent —
            # content-addressed — so losing is success.
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(dest):
                raise
        _gc_cache_locked()
    return dest


def _gc_cache_locked() -> None:
    root = cache_dir()
    try:
        entries = [os.path.join(root, e) for e in os.listdir(root)
                   if not e.endswith(".tmp")]
    except OSError:
        return
    entries = [e for e in entries if os.path.isdir(e)]
    if len(entries) <= CACHE_CAP:
        return
    entries.sort(key=lambda e: os.path.getmtime(e))
    for victim in entries[:len(entries) - CACHE_CAP]:
        logger.info("runtime_env cache GC: removing %s", victim)
        shutil.rmtree(victim, ignore_errors=True)


def delete_uri(uri: str, kv_stub) -> None:
    """Drop a package from the cluster KV and the local cache."""
    try:
        kv_stub.KvDel(pb.KvRequest(ns=KV_NS, key=uri))
    except Exception:  # noqa: BLE001
        pass
    with _cache_lock:
        shutil.rmtree(os.path.join(cache_dir(), uri[len(PKG_PREFIX):]),
                      ignore_errors=True)
