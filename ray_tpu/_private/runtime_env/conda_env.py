"""Conda environment materialization for runtime_env["conda"].

Reference: ``python/ray/_private/runtime_env/conda.py`` — environments
build once per content hash into a shared per-node cache and are reused
across workers; a spec may be inline YAML content (dict), a path to an
environment.yml, or the name of a pre-built env (resolved through
``conda env list``).

``RAY_TPU_CONDA_EXE`` overrides the conda binary (also how tests inject
a stub builder without a real conda installation).
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import shutil
import subprocess
from typing import Any, Optional

logger = logging.getLogger(__name__)



def _cache_root() -> str:
    return os.environ.get(
        "RAY_TPU_CONDA_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "ray_tpu", "conda"))


def _conda_exe() -> str:
    exe = os.environ.get("RAY_TPU_CONDA_EXE") or shutil.which("conda") \
        or shutil.which("mamba") or shutil.which("micromamba")
    if not exe:
        raise RuntimeError(
            "runtime_env['conda'] requires a conda/mamba binary on PATH "
            "(or RAY_TPU_CONDA_EXE)")
    return exe


def ensure_conda_env(spec: Any) -> str:
    """Materialize the env for ``spec``; returns the env prefix path."""
    if isinstance(spec, str) and not spec.endswith((".yml", ".yaml")):
        return _named_env_prefix(spec)
    if isinstance(spec, str):
        with open(spec) as f:
            content = f.read()
    else:
        content = json.dumps(spec, sort_keys=True)
    digest = hashlib.sha1(content.encode()).hexdigest()[:16]
    prefix = os.path.join(_cache_root(), digest)
    marker = os.path.join(prefix, ".ray_tpu_ready")
    os.makedirs(_cache_root(), exist_ok=True)
    # The cache is shared ACROSS worker processes on a node: an OS file
    # lock serializes builders per digest, or two workers would
    # `conda env create` into the same prefix (reference: conda.py uses
    # file locks for the same reason). flock also excludes threads within
    # one process (distinct fds of one file contend), so no process-wide
    # lock is held across a build — unrelated envs materialize in
    # parallel and cache hits never wait behind a 20-minute create.
    import fcntl

    with open(os.path.join(_cache_root(),
                           f"{digest}.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return prefix
        if os.path.exists(prefix):
            # A crashed/failed earlier build left a partial prefix with no
            # marker: clear it or every retry fails on the existing dir.
            shutil.rmtree(prefix, ignore_errors=True)
        yml = os.path.join(_cache_root(), f"{digest}.yml")
        if isinstance(spec, str):
            shutil.copyfile(spec, yml)
        else:
            _write_env_yaml(spec, yml)
        exe = _conda_exe()
        logger.info("building conda env %s (this happens once per spec)",
                    digest)
        try:
            subprocess.run([exe, "env", "create", "--yes", "-p", prefix,
                            "-f", yml],
                           check=True, capture_output=True, timeout=1800)
        except BaseException:
            shutil.rmtree(prefix, ignore_errors=True)
            raise
        with open(marker, "w") as f:
            f.write("ok")
        return prefix


def _write_env_yaml(spec: dict, path: str) -> None:
    """Minimal YAML emitter for the environment.yml subset conda reads
    (name/channels/dependencies with one level of pip nesting). Unknown
    keys raise: silently dropping them would cache a wrong env under the
    full spec's hash forever."""
    supported = ("name", "channels", "dependencies")
    unknown = [k for k in spec if k not in supported]
    if unknown:
        raise ValueError(
            f"unsupported environment.yml keys {unknown} (supported: "
            f"{supported}); write the spec to a file and pass its path "
            f"for full YAML support")
    lines = []
    for key in ("name", "channels", "dependencies"):
        value = spec.get(key)
        if value is None:
            continue
        if isinstance(value, str):
            lines.append(f"{key}: {value}")
            continue
        lines.append(f"{key}:")
        for item in value:
            if isinstance(item, dict):  # {"pip": [...]}
                for sub_key, sub_items in item.items():
                    lines.append(f"  - {sub_key}:")
                    lines.extend(f"    - {s}" for s in sub_items)
            else:
                lines.append(f"  - {item}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _named_env_prefix(name: str) -> str:
    """Resolve a pre-existing named env through ``conda env list``."""
    exe = _conda_exe()
    out = subprocess.run([exe, "env", "list", "--json"], check=True,
                         capture_output=True, timeout=60, text=True)
    for prefix in json.loads(out.stdout).get("envs", []):
        if os.path.basename(prefix) == name:
            return prefix
    raise RuntimeError(f"conda env {name!r} not found")


def site_packages_of(prefix: str) -> Optional[str]:
    hits = glob.glob(os.path.join(prefix, "lib", "python*",
                                  "site-packages"))
    return hits[0] if hits else None


__all__ = ["ensure_conda_env", "site_packages_of"]
