"""Per-spec-hash virtual environments for ``runtime_env={"pip": [...]}``.

Reference: ``python/ray/_private/runtime_env/pip.py`` — one venv per
distinct spec list, built once per node and shared by every worker using
that env. Differences for the agentless TPU runtime:

* installs run with ``--no-index --no-build-isolation`` so resolution
  never touches the network — specs must be local paths/wheels or already
  satisfied, which is the only sound behavior in air-gapped TPU pods;
* the venv is created with ``--system-site-packages`` so the baked-in
  scientific stack (jax et al.) stays importable;
* activation is ``sys.path`` insertion of the env's site-packages by the
  worker (pure-Python deps), not an interpreter re-exec — workers stay
  reusable across environments.
"""

from __future__ import annotations

import glob
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import List

logger = logging.getLogger(__name__)

_lock = threading.Lock()


def base_dir() -> str:
    return os.environ.get(
        "RAY_TPU_PIP_ENV_DIR",
        os.path.join(tempfile.gettempdir(), "ray_tpu_pip_envs"))


def env_hash(specs: List[str]) -> str:
    h = hashlib.sha256()
    for s in sorted(specs):
        h.update(s.encode())
        h.update(b"\0")
    return h.hexdigest()[:16]


def ensure_pip_env(specs: List[str]) -> str:
    """Build (once per node) the venv for ``specs`` and return its
    site-packages directory. Builds happen in a private tmp dir that is
    atomically renamed into place, so concurrent worker *processes* (the
    module lock only covers threads) race safely: the loser discards its
    build and adopts the winner's."""
    env_dir = os.path.join(base_dir(), env_hash(specs))
    marker = os.path.join(env_dir, ".ready")
    with _lock:
        if os.path.exists(marker):
            return _site_packages(env_dir)
        import shutil
        import time
        import venv

        tmp = f"{env_dir}.tmp.{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        venv.EnvBuilder(with_pip=True,
                        system_site_packages=True).create(tmp)
        py = os.path.join(tmp, "bin", "python")
        cmd = [py, "-m", "pip", "install", "--quiet", "--no-index",
               "--no-build-isolation", *specs]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=600)
        if proc.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"pip env build failed for {specs}: {proc.stderr[-2000:]}")
        with open(os.path.join(tmp, ".ready"), "w") as f:
            f.write("\n".join(specs))
        try:
            os.rename(tmp, env_dir)
        except OSError:
            # Another process won; wait for its marker then use that env.
            shutil.rmtree(tmp, ignore_errors=True)
            deadline = time.monotonic() + 600
            while not os.path.exists(marker):
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"pip env {env_dir} exists but never became ready")
                time.sleep(0.2)
    return _site_packages(env_dir)


def _site_packages(env_dir: str) -> str:
    matches = glob.glob(os.path.join(env_dir, "lib", "python*",
                                     "site-packages"))
    if not matches:
        raise RuntimeError(f"no site-packages under {env_dir}")
    return matches[0]


def delete_env(specs: List[str]) -> None:
    import shutil

    with _lock:
        shutil.rmtree(os.path.join(base_dir(), env_hash(specs)),
                      ignore_errors=True)


__all__ = ["ensure_pip_env", "delete_env", "env_hash", "base_dir"]
