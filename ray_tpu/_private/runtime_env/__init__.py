"""Runtime environments: per-task/actor working_dir, py_modules, env_vars, pip.

Reference: ``python/ray/_private/runtime_env/`` — the reference ships a
per-node agent that materializes environments before worker launch. The
TPU-native redesign is agentless: the driver packages local directories
into content-addressed zips stored in the GCS KV (``packaging.py``), and
the executing worker materializes them on first use (download + extract to
a per-node cache, venv build for pip specs) inside the worker process.
Pure-Python pip deps activate via ``sys.path`` rather than an interpreter
re-exec, which keeps workers reusable across environments.

Public surface:

* :func:`prepare` — driver-side: replace local paths in a runtime_env dict
  with uploaded ``pkg://`` URIs (reference:
  ``runtime_env/packaging.py`` upload path).
* :func:`apply` — worker-side: materialize and activate a prepared
  runtime_env in this process (reference:
  ``runtime_env/agent/runtime_env_agent.py:167`` CreateRuntimeEnv).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Any, Dict

from ray_tpu._private.runtime_env import packaging, pip_env

logger = logging.getLogger(__name__)


def prepare(renv: Dict[str, Any], kv_stub) -> Dict[str, Any]:
    """Driver-side prepare: each field's plugin uploads/validates its
    value (e.g. local directories become ``pkg://`` URIs any node can
    materialize). Fields without a plugin pass through with a warning."""
    from ray_tpu._private.runtime_env import plugin as plugin_mod

    out = dict(renv)
    for p in plugin_mod.plugins_for(renv):
        out[p.name] = p.prepare(renv[p.name], kv_stub)
    return out


def _purge_shadowed_modules(path: str) -> None:
    """Drop cached top-level modules that ``path`` provides, so the
    version this env ships wins over one a previous task already imported
    in this reused worker process."""
    try:
        entries = os.listdir(path)
    except OSError:
        return
    names = set()
    for e in entries:
        if e.endswith(".py") and e != "__init__.py":
            names.add(e[:-3])
        elif os.path.isdir(os.path.join(path, e)) and \
                os.path.exists(os.path.join(path, e, "__init__.py")):
            names.add(e)
    for name in names:
        for mod in [m for m in list(sys.modules)
                    if m == name or m.startswith(name + ".")]:
            sys.modules.pop(mod, None)


def apply(renv: Dict[str, Any], kv_stub):
    """Activate a prepared runtime_env in the current process: each
    field's plugin materializes into an :class:`EnvContext` (paths to
    prepend, env vars, cwd), which is then applied. Returns a zero-arg
    restore callable that undoes the process-level mutations — task
    workers call it after the task so a reused worker doesn't leak one
    task's environment into the next (the reference instead dedicates
    workers per env; actors here keep their env for life and skip
    restore)."""
    from ray_tpu._private.runtime_env import plugin as plugin_mod

    ctx = plugin_mod.EnvContext()
    for p in plugin_mod.plugins_for(renv):
        p.apply(renv[p.name], kv_stub, ctx)

    saved_env: Dict[str, Any] = {}
    added_paths: list = []
    old_cwd = os.getcwd()
    for k, v in ctx.env_vars.items():
        saved_env[k] = os.environ.get(k)
        os.environ[k] = str(v)
    if ctx.cwd:
        os.chdir(ctx.cwd)
    for p in ctx.paths:
        if p not in sys.path:
            sys.path.insert(0, p)
            added_paths.append(p)
        _purge_shadowed_modules(p)

    def restore() -> None:
        try:
            os.chdir(old_cwd)
        except OSError:
            pass
        for p in added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old

    return restore
