"""Runtime-env plugin framework.

Reference: ``python/ray/_private/runtime_env/plugin.py`` — every
runtime_env field is handled by a plugin keyed on that field's name, with
a driver-side prepare step (URI-ify / upload / validate) and a
worker-side apply step, ordered by priority. The built-in fields
(env_vars, working_dir, py_modules, pip, conda, container) are themselves
plugins registered here; user plugins register through
:func:`register_plugin` (worker processes import the module named in
``RAY_TPU_RUNTIME_ENV_PLUGINS`` so registration happens in every process
that applies environments).
"""

from __future__ import annotations

import abc
import importlib
import logging
import os
import threading
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class EnvContext:
    """Mutation collector for one apply(): plugins record process-level
    changes here; the framework performs them and builds the restore
    closure (reused task workers must not leak one task's env)."""

    def __init__(self):
        self.paths: List[str] = []       # prepended to sys.path
        self.env_vars: Dict[str, str] = {}
        self.cwd: Optional[str] = None

    def add_path(self, path: str) -> None:
        self.paths.append(path)

    def set_env(self, key: str, value: str) -> None:
        self.env_vars[key] = str(value)

    def set_cwd(self, path: str) -> None:
        self.cwd = path


class RuntimeEnvPlugin(abc.ABC):
    """One runtime_env field's lifecycle. ``name`` is the dict key the
    plugin owns; lower ``priority`` applies first (reference: plugin
    priority ordering)."""

    name: str = ""
    priority: int = 10
    # True when apply() may run a slow build (venv, conda, download):
    # the node manager prewarms such fields while placement is in flight.
    prewarmable: bool = False

    def prepare(self, value: Any, kv_stub) -> Any:
        """Driver-side: validate/upload; the return value replaces the
        field in the prepared runtime_env shipped with the task."""
        return value

    @abc.abstractmethod
    def apply(self, value: Any, kv_stub, ctx: EnvContext) -> None:
        """Worker-side: materialize the field, recording process changes
        on ``ctx``."""


_plugins: Dict[str, RuntimeEnvPlugin] = {}
_lock = threading.Lock()
_env_plugins_loaded = False


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin needs a non-empty name")
    with _lock:
        _plugins[plugin.name] = plugin


def get_plugin(name: str) -> Optional[RuntimeEnvPlugin]:
    _load_env_plugins()
    with _lock:
        return _plugins.get(name)


def plugins_for(renv: Dict[str, Any]) -> List[RuntimeEnvPlugin]:
    """The registered plugins relevant to ``renv``, priority-ordered.
    Unknown fields are tolerated (forward compatibility), with a one-time
    warning."""
    _load_env_plugins()
    with _lock:
        found = [p for name, p in _plugins.items() if name in renv]
        unknown = [k for k in renv if k not in _plugins]
    for k in unknown:
        if k not in _warned_unknown:
            _warned_unknown.add(k)
            logger.warning("no runtime_env plugin for field %r; ignoring",
                           k)
    return sorted(found, key=lambda p: p.priority)


_warned_unknown: set = set()


def _load_env_plugins() -> None:
    """Import plugin modules named in RAY_TPU_RUNTIME_ENV_PLUGINS
    (comma-separated import paths) once per process — workers apply
    environments in their own processes, so registration must re-run
    there (reference: RAY_RUNTIME_ENV_PLUGINS)."""
    global _env_plugins_loaded
    if _env_plugins_loaded:
        return
    _env_plugins_loaded = True
    for mod in filter(None, os.environ.get(
            "RAY_TPU_RUNTIME_ENV_PLUGINS", "").split(",")):
        try:
            importlib.import_module(mod.strip())
        except Exception:  # noqa: BLE001
            logger.exception("failed to import runtime_env plugin module "
                             "%r", mod)


# ------------------------------------------------------------ built-ins
class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 0

    def apply(self, value, kv_stub, ctx: EnvContext) -> None:
        for k, v in (value or {}).items():
            ctx.set_env(k, v)


class WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 1
    prewarmable = True

    def prepare(self, value, kv_stub):
        from ray_tpu._private.runtime_env import packaging

        if value and not packaging.is_uri(value) and os.path.isdir(value):
            return packaging.upload_directory(value, kv_stub)
        return value

    def apply(self, value, kv_stub, ctx: EnvContext) -> None:
        from ray_tpu._private.runtime_env import packaging

        if not value:
            return
        path = packaging.ensure_local(value, kv_stub) \
            if packaging.is_uri(value) else value
        ctx.set_cwd(path)
        ctx.add_path(path)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 2
    prewarmable = True

    def prepare(self, value, kv_stub):
        from ray_tpu._private.runtime_env import packaging

        # A py_modules entry is itself the importable module/package, so
        # it nests under its own name in the zip (reference py_modules
        # semantics: ``import <basename>`` works on the worker).
        return [
            packaging.upload_directory(
                m, kv_stub,
                prefix=os.path.basename(os.path.normpath(m)))
            if not packaging.is_uri(m) and os.path.isdir(m) else m
            for m in (value or [])
        ]

    def apply(self, value, kv_stub, ctx: EnvContext) -> None:
        from ray_tpu._private.runtime_env import packaging

        for mod in value or []:
            path = packaging.ensure_local(mod, kv_stub) \
                if packaging.is_uri(mod) else mod
            ctx.add_path(path)


class PipPlugin(RuntimeEnvPlugin):
    name = "pip"
    priority = 3
    prewarmable = True

    def apply(self, value, kv_stub, ctx: EnvContext) -> None:
        from ray_tpu._private.runtime_env import pip_env

        if value:
            ctx.add_path(pip_env.ensure_pip_env(list(value)))


class CondaPlugin(RuntimeEnvPlugin):
    """Conda environments (reference: ``_private/runtime_env/conda.py``).

    ``conda`` may be a dict (environment.yml content), a path to an
    environment.yml, or the name of a pre-existing conda env. The env is
    built once per content hash into a shared cache; activation puts its
    site-packages (and bin on PATH) into the worker process.
    """

    name = "conda"
    priority = 3
    prewarmable = True

    def apply(self, value, kv_stub, ctx: EnvContext) -> None:
        from ray_tpu._private.runtime_env import conda_env

        if not value:
            return
        env_path = conda_env.ensure_conda_env(value)
        site = conda_env.site_packages_of(env_path)
        if site:
            ctx.add_path(site)
        bin_dir = os.path.join(env_path, "bin")
        if os.path.isdir(bin_dir):
            # Compose with a PATH the env_vars plugin may already have
            # recorded (overwriting it would drop the user's entries).
            base = ctx.env_vars.get("PATH", os.environ.get("PATH", ""))
            ctx.set_env("PATH", bin_dir + os.pathsep + base)


class ContainerPlugin(RuntimeEnvPlugin):
    """Container image environments (reference:
    ``_private/runtime_env/image_uri.py``). Containers wrap WORKER LAUNCH
    (the process must start inside the image), which this agentless
    runtime applies at node-manager worker spawn via
    :func:`container_command`; in-process apply only validates and
    exports the image for tooling."""

    name = "container"
    priority = 0

    def prepare(self, value, kv_stub):
        if isinstance(value, str):
            value = {"image": value}
        if not isinstance(value, dict) or not value.get("image"):
            raise ValueError(
                "runtime_env['container'] needs {'image': <uri>, "
                "'run_options': [...]}")
        return value

    def apply(self, value, kv_stub, ctx: EnvContext) -> None:
        if not value:
            return
        ctx.set_env("RAY_TPU_CONTAINER_IMAGE", value["image"])
        if os.environ.get("RAY_TPU_CONTAINER_IMAGE") != value["image"]:
            # This worker was NOT launched inside the image: in-process
            # activation cannot retrofit container isolation. Be loud —
            # silently running on the host with the wrong dependencies is
            # worse than failing.
            logger.warning(
                "runtime_env['container'] image %r requested, but this "
                "worker is not running inside it; the task executes on "
                "the host. Launch container workers via "
                "plugin.container_command (e.g. in the cluster config's "
                "worker startup) for real isolation.", value["image"])


def container_command(container: Dict[str, Any],
                      worker_cmd: List[str]) -> List[str]:
    """Wrap a worker launch command to run inside the declared image
    (podman/docker, host networking so the worker can reach the node
    manager). Used by the node manager when a lease carries a container
    runtime_env."""
    engine = container.get("engine") or os.environ.get(
        "RAY_TPU_CONTAINER_ENGINE", "podman")
    cmd = [engine, "run", "--rm", "--network=host",
           "-v", f"{os.getcwd()}:{os.getcwd()}"]
    cmd += [str(o) for o in container.get("run_options", [])]
    cmd += [container["image"]]
    cmd += worker_cmd
    return cmd


for _p in (EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(),
           PipPlugin(), CondaPlugin(), ContainerPlugin()):
    register_plugin(_p)


__all__ = ["RuntimeEnvPlugin", "EnvContext", "register_plugin",
           "get_plugin", "plugins_for", "container_command"]
