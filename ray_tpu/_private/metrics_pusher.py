"""Per-process metrics pusher: ships registry snapshots to the head TSDB.

Reference: each reference node runs a metrics agent that exports to
Prometheus; here every cluster process (driver, worker, node manager,
node agent) pushes its process-local registry to the GCS over the
existing pubsub plane (``Publish`` on the ``METRICS`` channel) where the
head-side :class:`~ray_tpu._private.tsdb.TimeSeriesDB` ingests it.

One pusher per (process, GCS address). Processes that HOST an in-process
GCS (the single-process test clusters, `ray-tpu start --head`) skip the
RPC hop entirely — the GCS samples the shared process-local registry
itself (gcs/server.py), and a pusher would double-ingest every sample.
A pusher that fails to publish repeatedly (its cluster died) stops and
deregisters itself.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

METRICS_CHANNEL = "METRICS"
# 2s default keeps head-side ingest load modest with hundreds of pushing
# processes (far finer than Prometheus' 15s scrape norm); deployments and
# tests tune RAY_TPU_METRICS_PUSH_INTERVAL_S.
DEFAULT_INTERVAL_S = 2.0
MAX_CONSECUTIVE_FAILURES = 10

_lock = threading.Lock()
_pushers: Dict[str, "MetricsPusher"] = {}
_refs: Dict[str, int] = {}  # per-address ensure() count (shared pushers)
_inprocess_gcs: set = set()


def note_inprocess_gcs(address: str) -> None:
    """Record that this process hosts the GCS at ``address`` (the GCS
    samples the registry locally; pushers to it are redundant)."""
    with _lock:
        _inprocess_gcs.add(address)
        _refs.pop(address, None)
        pusher = _pushers.pop(address, None)
    if pusher is not None:
        pusher.stop()


def forget_inprocess_gcs(address: str) -> None:
    with _lock:
        _inprocess_gcs.discard(address)


def push_interval_s() -> float:
    try:
        return float(os.environ.get("RAY_TPU_METRICS_PUSH_INTERVAL_S",
                                    DEFAULT_INTERVAL_S))
    except ValueError:
        return DEFAULT_INTERVAL_S


def ensure_pusher(gcs_address: str,
                  labels: Optional[Dict[str, str]] = None
                  ) -> Optional["MetricsPusher"]:
    """Start (or return) this process's pusher toward ``gcs_address``."""
    if not gcs_address or \
            os.environ.get("RAY_TPU_METRICS_PUSH", "1") == "0":
        return None
    with _lock:
        if gcs_address in _inprocess_gcs:
            return None
        _refs[gcs_address] = _refs.get(gcs_address, 0) + 1
        pusher = _pushers.get(gcs_address)
        if pusher is not None and pusher.alive:
            return pusher
        pusher = _pushers[gcs_address] = MetricsPusher(
            gcs_address, labels or {})
    return pusher


def release_pusher(gcs_address: str) -> None:
    """Drop one component's claim on the address's shared pusher; the
    pusher stops only when the last claimant releases (a driver's
    shutdown must not silence a co-resident node manager's metrics)."""
    pusher = None
    with _lock:
        n = _refs.get(gcs_address, 0) - 1
        if n > 0:
            _refs[gcs_address] = n
        else:
            _refs.pop(gcs_address, None)
            pusher = _pushers.pop(gcs_address, None)
    if pusher is not None:
        pusher.stop()


def stop_all() -> None:
    with _lock:
        pushers = list(_pushers.values())
        _pushers.clear()
        _refs.clear()
    for p in pushers:
        p.stop()


class MetricsPusher:
    def __init__(self, gcs_address: str, labels: Dict[str, str]):
        self.gcs_address = gcs_address
        self.labels = {"pid": str(os.getpid()), **labels}
        self._stop = threading.Event()
        self._failures = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metrics-pusher")
        self._thread.start()

    @property
    def alive(self) -> bool:
        return not self._stop.is_set()

    def stop(self) -> None:
        self._stop.set()

    def _deregister(self) -> None:
        with _lock:
            if _pushers.get(self.gcs_address) is self:
                del _pushers[self.gcs_address]

    def _loop(self) -> None:
        from ray_tpu._private import rpc
        from ray_tpu.protobuf import ray_tpu_pb2 as pb
        from ray_tpu.util import metrics

        gcs = rpc.get_stub("GcsService", self.gcs_address)
        interval = push_interval_s()
        while not self._stop.wait(interval):
            samples = metrics.collect_samples()
            if not samples:
                continue
            batch = {"ts": time.time(), "labels": self.labels,
                     "samples": samples}
            try:
                gcs.Publish(pb.PublishRequest(
                    channel=METRICS_CHANNEL,
                    data=pickle.dumps(batch)), timeout=5)
                self._failures = 0
            except Exception:  # noqa: BLE001 — head briefly unreachable
                self._failures += 1
                if self._failures >= MAX_CONSECUTIVE_FAILURES:
                    # Cluster is gone for good (sequential test clusters,
                    # torn-down heads): stop rather than spin forever.
                    self._stop.set()
        self._deregister()
