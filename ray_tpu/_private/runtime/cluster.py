"""ClusterRuntime: client of a multi-process ray_tpu cluster.

Connects the driver/worker process to this node's daemon and the cluster
control plane (reference analog: the Cython CoreWorker connecting to the
raylet + GCS, ``python/ray/_raylet.pyx:2953``).
"""

from __future__ import annotations


class ClusterRuntime:
    @classmethod
    def connect(cls, address: str, namespace: str = "default"):
        raise RuntimeError(
            "ray_tpu cluster mode is not available yet in this build: "
            f"cannot connect to {address!r}. Use ray_tpu.init() with no "
            "address for the in-process runtime.")
