"""ClusterRuntime: CoreRuntime implementation for multi-process clusters.

Reference: the CoreWorker (``src/ray/core_worker/core_worker.cc`` — SURVEY.md
C25-C30) collapsed to its essential protocol, python-side:

* normal tasks follow the lease protocol of §3.2: request a worker lease from
  the local node manager, follow spillback redirects, push the task directly
  to the leased worker (``normal_task_submitter.cc:23,202,538``), return the
  worker afterwards;
* actor tasks go straight to the actor's worker with per-caller sequence
  numbers for ordering (``actor_task_submitter.cc:158,580``) — no raylet on
  the hot path; actor restarts re-resolve the address through the GCS;
* objects: small values ride inline in the push reply into the caller's
  memory store; larger values go to the node object store with locations
  registered in the GCS directory and chunk-streamed between nodes on demand
  (C12/C13/C29).
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu import exceptions
from ray_tpu._private import metrics_defs as mdefs
from ray_tpu._private import rpc
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.options import is_streaming
from ray_tpu._private.runtime.interface import CoreRuntime
from ray_tpu.protobuf import ray_tpu_pb2 as pb

logger = logging.getLogger(__name__)

INLINE_RESULT_MAX = 100 * 1024  # reference: >100KB promoted to plasma
PUSH_TIMEOUT_S = 24 * 3600.0


def dumps(value: Any) -> bytes:
    return cloudpickle.dumps(value)


def loads(data) -> Any:
    return cloudpickle.loads(data)


# Store-object wire formats: plain cloudpickle (legacy writers) or the
# magic-prefixed Serializer format whose out-of-band buffers let large
# values be written into shm with a single copy (put hot path).
STORE_MAGIC = b"RTS1"


def loads_store(data) -> Any:
    mv = memoryview(data)
    if mv.nbytes >= 4 and bytes(mv[:4]) == STORE_MAGIC:
        from ray_tpu._private.serialization import (SerializedObject,
                                                    Serializer)

        return Serializer().deserialize(SerializedObject.parse(mv[4:]))
    return cloudpickle.loads(data)


def dumps_payload(value: Any) -> Tuple[bytes, List[bytes]]:
    """Serialize a task payload, returning (wire bytes, contained ref ids).

    Uses the framework Serializer so ObjectRefs nested anywhere inside
    args/kwargs are collected — the submitter pins each contained ref for
    the task's flight time (reference: task-arg pinning in
    reference_count.h; round-2 advisor finding #1: top-level-only pinning
    let containerized refs hit zero mid-flight).
    """
    from ray_tpu._private.serialization import Serializer

    s = Serializer().serialize(value)
    return s.to_bytes(), list(s.contained_refs)


def loads_payload(data) -> Tuple[Any, int]:
    """Deserialize a task payload. Returns (value, n_contained_refs).

    Deserializing registers a borrow (+1) for every contained ref via the
    ObjectRef constructor; executors must flush those borrows to the GCS
    *before* running user code so the submitter's pin release (-1, sent
    after the push returns) can never be observed first.
    """
    from ray_tpu._private.serialization import SerializedObject, Serializer

    s = SerializedObject.parse(data)
    return Serializer().deserialize(s), len(s.contained_refs)


def put_bytes_to_node(node_stub, oid_binary: bytes, data: bytes,
                      owner: str) -> bool:
    """Store serialized bytes on a node: large payloads go through a
    client-created shm segment (zero-copy data plane, metadata-only RPC);
    small ones ride inline in the RPC. Returns False when the store
    REJECTED the object (full even after spilling) — callers must not
    assume the object is fetchable."""
    from ray_tpu._private.shm import ShmClient

    if len(data) > INLINE_RESULT_MAX and ShmClient.available():
        # Full oid hex: truncating would collide every object of one task
        # (they differ only in the trailing 4-byte index).
        seg = f"/rtpu.{oid_binary.hex()}"
        if ShmClient.create_segment(seg, data):
            reply = node_stub.PutObject(pb.PutObjectRequest(
                object_id=oid_binary, shm_name=seg, size=len(data),
                owner=owner))
            return not reply.rejected
    reply = node_stub.PutObject(pb.PutObjectRequest(
        object_id=oid_binary, data=data, owner=owner))
    return not reply.rejected


def read_object_reply(reply) -> Any:
    """Materialize a GetObjectReply: map the shm segment when present.

    The shm read is ZERO-COPY: the segment is mmapped and deserialized
    in place — pickle-5 out-of-band buffers become sub-views of the
    mapping, so a large numpy result costs zero data copies end to end
    (the r03→r05 ``get_large_gb_per_s`` collapse was the old
    read-into-bytes path paying a full copy before deserializing).
    ``read_segment`` stays as the fallback for hosts without a
    file-backed /dev/shm."""
    from ray_tpu._private.shm import ShmClient

    if reply.shm_name:
        view = ShmClient.map_segment_view(reply.shm_name, reply.size)
        if view is not None:
            return loads_store(view)
        data = ShmClient.read_segment(reply.shm_name, reply.size)
        if data is None:
            return None
        return loads_store(data)
    return loads_store(reply.data)


def _run_callback(cb) -> None:
    try:
        cb()
    except Exception:  # noqa: BLE001 — a future callback must not leak
        logger.exception("future completion callback failed")


def _future_set(fut: Future, value: Any) -> None:
    """Resolve an ObjectRef future with get() semantics: stored task
    errors become the future's exception, everything else its result."""
    if fut.done():
        return
    if isinstance(value, exceptions.RayTaskError):
        fut.set_exception(value.as_instanceof_cause())
    elif isinstance(value, exceptions.RayTpuError):
        fut.set_exception(value)
    else:
        fut.set_result(value)


class _PullManager:
    """Receiver-side transfer admission (reference C13 PullManager,
    ``pull_manager.h:53``): bounds the bytes of concurrently in-flight
    pulls and dedups concurrent pulls of one object inside a process."""

    def __init__(self, budget_bytes: int):
        self._budget = max(budget_bytes, 1)
        self._avail = self._budget
        self._cv = threading.Condition()
        self._inflight: Dict[bytes, threading.Event] = {}

    def _cost(self, size: int) -> int:
        return min(max(size, 1), self._budget)

    def begin(self, oid: bytes, size: int, wait_s: float = 60.0):
        """Admit a pull. Returns None when this caller should pull, or the
        in-flight pull's Event to wait on when someone else already is.
        ``wait_s`` bounds the budget wait (callers pass their remaining
        get() deadline); expiry fails open — admission is advisory and
        must never extend a timeout."""
        cost = self._cost(size)
        deadline = time.monotonic() + max(wait_s, 0.0)
        with self._cv:
            ev = self._inflight.get(oid)
            if ev is not None:
                return ev
            while self._avail < cost:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # fail open: a stuck budget must not deadlock
                self._cv.wait(timeout=min(remaining, 1.0))
                ev = self._inflight.get(oid)
                if ev is not None:
                    return ev
            self._avail -= cost
            self._inflight[oid] = threading.Event()
            return None

    def end(self, oid: bytes, size: int) -> None:
        with self._cv:
            self._avail += self._cost(size)
            ev = self._inflight.pop(oid, None)
            self._cv.notify_all()
        if ev is not None:
            ev.set()


class _HexId(str):
    """Node/worker ids travel as hex strings in this runtime; ``.hex()``
    (the ID-object protocol runtime_context expects) is identity."""

    def hex(self) -> str:  # type: ignore[override]
        return str(self)


class ClusterRuntime(CoreRuntime):
    def __init__(self, gcs_address: str, node_address: str,
                 namespace: str = "default", is_worker: bool = False,
                 worker_id: Optional[str] = None,
                 node_id: Optional[str] = None):
        self.gcs_address = gcs_address
        self.node_address = node_address
        self.namespace = namespace
        self.is_worker = is_worker
        self.worker_id = worker_id or uuid.uuid4().hex
        self.node_id = _HexId(node_id or "")
        self.job_id = JobID.from_int(1)
        self.gcs = rpc.get_stub("GcsService", gcs_address)
        self.node = rpc.get_stub("NodeService", node_address)
        self.memory = MemoryStore()
        self._pulls = _PullManager(int(os.environ.get(
            "RAY_TPU_PULL_BUDGET_BYTES", 512 << 20)))
        self._spread_idx = 0
        self._spread_lock = threading.Lock()
        self._node_addr_cache = None
        # The pool carries every background work item (task submits,
        # actor pushes, prefetches, stream polls): it stays WIDE so slow
        # tasks can't head-of-line block gets and actor calls. Raw submit
        # throughput is protected separately: _submit_slots bounds how
        # many submitters are in their RPC-ACTIVE phase at once — beyond
        # ~8 concurrently-active submitters, GIL + grpc contention makes
        # submission slower than sequential (measured: 150 vs 500
        # tasks/s). Slots are NOT held during task execution.
        self._pool = ThreadPoolExecutor(max_workers=64,
                                        thread_name_prefix="submit")
        # Results of locally-submitted in-flight tasks arrive via the push
        # reply — getters wait on these events instead of probing the
        # store/directory (3 RPCs per spin, the r3 roundtrip bottleneck).
        self._pending_results: Dict[bytes, threading.Event] = {}
        # oid -> completion callbacks (as_future): invoked by the thread
        # that applies the push result, so futures resolve without a
        # parked waiter thread each (the r5 async fan-in cost: one pool
        # thread per in-flight future).
        self._pending_callbacks: Dict[bytes, List] = {}
        self._pending_res_lock = threading.Lock()
        # Small-put flusher: puts enqueue here; one thread batches them
        # into PutObjectBatch RPCs (an RPC per 1KB put made put() RPC-bound).
        from collections import deque

        self._put_q = deque()
        self._put_cv = threading.Condition()
        self._put_flusher_started = False
        # Per-lease-signature task queues drained by lease-holding runner
        # threads (see _dispatch_task).
        self._sig_queues: Dict[Any, dict] = {}
        self._sig_lock = threading.Lock()
        # Cancellation (reference: CoreWorker::CancelTask,
        # core_worker.h:961): cancelled task ids are observed by every
        # dispatch stage (dep-wait, sig queue, lease negotiation, push);
        # running tasks are interrupted via a CancelTask RPC to the worker
        # recorded in _running_locs. _children maps a task executing ON
        # THIS worker -> tasks it submitted, for recursive cancel.
        self._cancel_lock = threading.Lock()
        self._cancelled_tasks: set = set()
        self._running_locs: Dict[bytes, str] = {}
        self._children: Dict[bytes, list] = {}
        # Locality-hint directory cache: oid -> (ts, size, node_ids).
        self._loc_cache: Dict[bytes, tuple] = {}
        # Inline results not yet flushed to the node store (flushed on
        # ref escape — see _apply_push_result / _flush_escaped), plus the
        # sticky set of ids whose refs have left this process.
        self._lazy_results: Dict[bytes, bytes] = {}
        self._escaped_ids: set = set()
        self._submit_slots = threading.BoundedSemaphore(
            int(os.environ.get("RAY_TPU_SUBMIT_RPC_SLOTS", 8)))
        # Completion processing uses its OWN slots: if tails shared the
        # submit semaphore, lease-waiting submitters (blocked until a
        # worker frees) would starve the very result processing that
        # frees workers — a deadlock cycle.
        self._completion_slots = threading.BoundedSemaphore(
            int(os.environ.get("RAY_TPU_SUBMIT_RPC_SLOTS", 8)))
        self._actor_cache: Dict[bytes, pb.ActorInfo] = {}
        self._actor_dead: Dict[bytes, str] = {}
        self._actor_create_pins: Dict[bytes, List[bytes]] = {}
        self._actor_seq: Dict[bytes, int] = {}
        self._actor_session: Dict[bytes, int] = {}
        self._actor_window: Dict[bytes, dict] = {}
        self._actor_lock = threading.Lock()
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._shutdown = False
        # Short-TTL cache of granted worker leases keyed by resource shape
        # (see _lease_signature): same-shaped tasks pipeline onto a held
        # lease instead of paying lease/return per task.
        self._lease_cache: Dict[Any, List[dict]] = {}
        self._lease_cache_lock = threading.Lock()
        self._lease_reaper_started = False
        # Ownership: this process owns the objects its tasks/puts create.
        # Local ObjectRef lifetimes feed the distributed refcount (GCS sums
        # per-holder counts; zero => cluster-wide free). Lineage (the creating
        # TaskSpec) stays pinned while this owner holds refs, enabling
        # re-execution when every stored copy is lost (reference:
        # reference_count.h:66 + task_manager.h:274 ResubmitTask).
        from ray_tpu._private.refcount import ReferenceCounter

        self.refs = ReferenceCounter(self.gcs, self.worker_id,
                                     on_local_zero=self._on_ref_zero,
                                     node_id=node_id or "",
                                     is_driver=not is_worker)
        self._lineage: Dict[bytes, pb.TaskSpec] = {}
        self._lineage_lock = threading.Lock()
        self._reconstructing: Dict[bytes, threading.Event] = {}
        # Tasks whose first execution finished (success or error): a fetch
        # miss on their returns means "produced then lost", not "pending".
        # Pruned alongside lineage: when the last lineage entry for a task's
        # returns is dropped, the done-marker goes too (weak #7 r2: these
        # grew without bound in long-lived drivers).
        self._task_done: set = set()
        self._task_lineage_count: Dict[bytes, int] = {}
        # task id -> raw promoted-payload bytes, retained while lineage
        # lives so reconstruction can re-put the payload if the node holding
        # its only store copy died — memory cost matches the inline-payload
        # spec the lineage used to pin, so this is not a regression. (The
        # payload's object id itself lives on the lineage spec.)
        self._lineage_payload_bytes: Dict[bytes, bytes] = {}
        # GCS pubsub drives actor-address resolution and object-readiness
        # wakeups (no sleep-polling on those paths — reference:
        # pubsub/publisher.h:297). The condition is notified on every
        # relevant event; waiters use it with a coarse safety timeout.
        self._ready_cond = threading.Condition()
        self._sub_thread = threading.Thread(
            target=self._subscriber_loop, daemon=True, name="gcs-subscriber")
        self._sub_thread.start()
        from ray_tpu._private import metrics_pusher, xla_monitor

        metrics_pusher.ensure_pusher(
            gcs_address, labels={"role": "worker" if is_worker
                                 else "driver"})
        # XLA plane wiring: telemetry destination + capture-listener
        # target for any jit work this process runs (lazy — processes
        # that never compile pay nothing beyond this address note).
        xla_monitor.connect(gcs_address, node_id=node_id)

    @classmethod
    def connect(cls, address: str, namespace: str = "default") -> "ClusterRuntime":
        gcs = rpc.get_stub("GcsService", address)
        nodes = [n for n in gcs.GetNodes(pb.GetNodesRequest(), timeout=10).nodes
                 if n.alive]
        if not nodes:
            raise ConnectionError(f"no alive nodes in cluster at {address}")
        local = sorted(nodes, key=lambda n: n.node_id)[0]
        return cls(address, local.address, namespace=namespace,
                   node_id=local.node_id)

    def _refresh_local_node(self) -> bool:
        """Fail over to another alive node when the local raylet is gone
        (reference analog: a worker whose raylet dies is itself dead — but a
        *driver* reconnects, and our in-process test clusters kill node
        managers under live drivers routinely)."""
        try:
            nodes = [n for n in
                     self.gcs.GetNodes(pb.GetNodesRequest(), timeout=5).nodes
                     if n.alive]
        except Exception:  # noqa: BLE001
            return False
        for n in nodes:
            if n.address == self.node_address:
                return True  # still listed alive; keep it
        if not nodes:
            return False
        pick = sorted(nodes, key=lambda n: n.node_id)[0]
        logger.warning("local node %s unreachable; failing over to %s",
                       self.node_address, pick.address)
        self.node_address = pick.address
        self.node = rpc.get_stub("NodeService", pick.address)
        self.node_id = _HexId(pick.node_id)
        return True

    # ------------------------------------------------------------- pubsub
    def _subscriber_loop(self):
        """Long-lived GCS subscription for ACTOR and OBJECT_LOC channels.

        Reconnects with backoff on stream failure (incl. GCS restart — the
        resubscribe path of the reference's GCS client).
        """
        sub_id = f"rt-{self.worker_id[:12]}"
        # Drivers also stream worker logs (reference: log_to_driver);
        # workers must not, or their re-printing would loop forever.
        channels = ["ACTOR", "OBJECT_LOC"]
        if not self.is_worker and \
                os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0":
            channels.append("LOG")
        while not self._shutdown:
            try:
                stream = self.gcs.Subscribe(pb.SubscribeRequest(
                    channels=channels, subscriber_id=sub_id))
                self._sub_stream = stream
                for msg in stream:
                    if self._shutdown:
                        return
                    if msg.channel == "ACTOR":
                        self._on_actor_event(msg.data)
                    elif msg.channel == "LOG":
                        self._on_log_event(msg.data)
                    else:
                        with self._ready_cond:
                            self._ready_cond.notify_all()
            except Exception:  # noqa: BLE001 — stream broken; resubscribe
                if self._shutdown:
                    return
                time.sleep(0.2)

    def _on_log_event(self, data: bytes) -> None:
        """Print a worker's mirrored output with its identity prefix
        (reference: ``log_to_driver`` formatting in worker.py)."""
        import sys

        try:
            rec = pickle.loads(data)
            # Scope to this driver's namespace (the analog of the
            # reference's per-job log routing; drivers sharing a namespace
            # share worker logs).
            if rec.get("ns", "default") != self.namespace:
                return
            out = sys.stderr if rec.get("stream") == "stderr" else sys.stdout
            for line in rec.get("lines", ()):
                print(f"({rec.get('name', '?')} pid={rec.get('pid', '?')}) "
                      f"{line}", file=out, flush=True)
        except Exception:  # noqa: BLE001
            pass

    def _on_actor_event(self, data: bytes):
        try:
            info = pb.ActorInfo()
            info.ParseFromString(data)
        except Exception:  # noqa: BLE001
            return
        if info.state in ("ALIVE", "DEAD"):
            self._release_create_pins(bytes(info.actor_id))
        with self._actor_lock:
            if info.state == "ALIVE":
                self._actor_cache[bytes(info.actor_id)] = info
            else:
                self._actor_cache.pop(bytes(info.actor_id), None)
                if info.state == "DEAD":
                    # Remember terminal states so waiters fail fast
                    # (bounded: long-lived drivers churn many actors).
                    self._actor_dead[bytes(info.actor_id)] = \
                        info.death_cause or "actor is dead"
                    while len(self._actor_dead) > 4096:
                        self._actor_dead.pop(next(iter(self._actor_dead)))
        with self._ready_cond:
            self._ready_cond.notify_all()

    # ------------------------------------------------------------- references
    def add_local_reference(self, ref: ObjectRef) -> None:
        self.refs.incr(ref.id().binary())

    def remove_local_reference(self, object_id) -> None:
        if not self._shutdown:
            self.refs.decr(object_id.binary())

    def _on_ref_zero(self, oid: bytes) -> None:
        """Local count hit zero: evict the in-process copy and unpin lineage.
        (The cluster-wide free happens at the GCS when *all* holders drop.)"""
        from ray_tpu._private.ids import ObjectID

        self.memory.delete([ObjectID(oid)])
        self._lazy_results.pop(oid, None)
        self._escaped_ids.discard(oid)
        payload_oid = None
        with self._lineage_lock:
            spec = self._lineage.pop(oid, None)
            if spec is not None:
                task_key = ObjectID(oid).task_id().binary()
                n = self._task_lineage_count.get(task_key, 0) - 1
                if n <= 0:
                    self._task_lineage_count.pop(task_key, None)
                    self._task_done.discard(task_key)
                    self._reconstructing.pop(task_key, None)
                    self._lineage_payload_bytes.pop(task_key, None)
                    payload_oid = bytes(spec.payload_ref) or None
                else:
                    self._task_lineage_count[task_key] = n
        if payload_oid is not None:
            # Lineage gone: the promoted payload can go too. Decremented
            # outside _lineage_lock — the zero callback re-enters here.
            self.refs.decr(payload_oid)

    # ---------------------------------------------------------------- objects
    def put(self, value: Any, owner_ref: Optional[ObjectRef] = None) -> ObjectRef:
        # Puts are scoped to a per-process random task id so object ids never
        # collide across processes (reference: put index within caller task).
        if not hasattr(self, "_put_task_id"):
            self._put_task_id = TaskID.for_normal_task(self.job_id)
        oid = ObjectID.from_task(self._put_task_id, self._next_put_index())
        from ray_tpu._private.serialization import Serializer

        s = Serializer().serialize(value)
        # Refs nested inside a put value escape with it.
        if s.contained_refs:
            self._flush_escaped(list(s.contained_refs))
        # Owner semantics (reference: small objects live in the owner's
        # in-process store): the value is immediately visible to this
        # process; the node-store copy + directory registration that remote
        # readers need flush asynchronously (batched — see _put_flush_loop).
        # Remote fetches racing the flush retry through the directory.
        self.memory.put(oid, value)
        if s.total_bytes() > INLINE_RESULT_MAX:
            # Large value: serialize straight into a client-created shm
            # segment on the caller thread (single copy; deferring would
            # let the caller mutate buffers before a snapshot). Only the
            # metadata registration rides the async batch.
            self._put_large(oid, s)
        else:
            self._enqueue_put(("data", oid, STORE_MAGIC + s.to_bytes()))
        return ObjectRef(oid, owner_address=self.node_address)

    def _put_large(self, oid: ObjectID, s) -> None:
        from ray_tpu._private.shm import ShmClient

        wire = s.wire_size()
        seg = f"/rtpu.{oid.binary().hex()}"
        if ShmClient.available() and ShmClient.create_segment_vectored(
                seg, s.to_parts(STORE_MAGIC)):
            size = 4 + wire
            # Register synchronously over the node fastpath: the metadata
            # frame is tiny, and skipping the flusher removes the
            # cross-thread wakeups that contended with the NEXT put's
            # writev on small hosts (plus the object is fetchable the
            # moment put() returns). Flusher remains the fallback.
            if not self._register_shm_sync(oid, seg, size):
                self._enqueue_put(("shm", oid, seg, size))
            return
        # No shm: legacy inline/bytes path.
        self._enqueue_put(("data", oid, STORE_MAGIC + s.to_bytes()))

    def _register_shm_sync(self, oid: ObjectID, seg: str,
                           size: int) -> bool:
        from ray_tpu._private import fastpath

        batch = pb.PutObjectBatchRequest()
        batch.items.append(pb.PutObjectRequest(
            object_id=oid.binary(), shm_name=seg, size=size,
            owner=self.worker_id))
        # Short timeout: this is a tiny metadata frame on the user's put()
        # call path — a stalled node must degrade to the async flusher,
        # not hang the caller. Registration is idempotent, so a timed-out
        # frame that DID land is harmlessly re-sent by the flusher.
        status, reply = fastpath.call_proto(
            self._node_fast_address(), fastpath.KIND_PUT_BATCH, batch,
            pb.PutObjectBatchReply, timeout=2)
        if status != "ok":
            return False  # transport/no client: let the flusher handle it
        if reply.rejected and reply.rejected[0]:
            # Store full: the node unlinked the segment; rebuild from the
            # live value and retry through the flusher's backoff path.
            self._requeue_rejected_shm(("shm", oid, seg,
                                        time.monotonic() + 60.0))
        return True

    def _requeue_rejected_shm(self, item: tuple) -> None:
        """Rebuild a rejected zero-copy put's segment from the live value
        (the node unlinked the original) and queue it again, preserving
        the item's original deadline."""
        from ray_tpu._private.serialization import Serializer
        from ray_tpu._private.shm import ShmClient

        oid, seg, deadline = item[1], item[2], item[-1]
        value = self.memory.get_if_ready(oid, default=None)
        if value is None:
            return  # freed meanwhile: nothing to re-ship
        s = Serializer().serialize(value)
        if ShmClient.create_segment_vectored(seg, s.to_parts(STORE_MAGIC)):
            with self._put_cv:
                self._put_q.append(("shm", oid, seg,
                                    4 + s.wire_size(), deadline))

    def _flush_escaped(self, oid_bins) -> None:
        """Mark refs as ESCAPED (leaving this process inside a task
        payload / actor args / put value / pickled result) and flush any
        lazily-held bytes to the node store so remote consumers resolve
        them through the directory. Escape is sticky: a ref can escape
        BEFORE its task finishes (a reduce task submitted on a map
        task's in-flight returns), so arrival checks the set too."""
        for ob in oid_bins:
            self._escaped_ids.add(ob)
            data = self._lazy_results.pop(ob, None)
            if data is not None:
                self._enqueue_put(("data", ObjectID(ob), data))

    NODE_FAST_REFRESH_S = 30.0

    def _node_fast_address(self) -> str:
        """The local node manager's binary object plane address, learned
        lazily from the GCS node table ("" until known)."""
        now = time.monotonic()
        cached = getattr(self, "_node_fast_cache", None)
        if cached is not None and now - cached[0] < self.NODE_FAST_REFRESH_S:
            return cached[1]
        addr = ""
        try:
            for n in self.gcs.GetNodes(pb.GetNodesRequest()).nodes:
                if n.address == self.node_address and n.alive:
                    addr = n.fast_address
                    break
        except Exception:  # noqa: BLE001 — fall back to gRPC
            pass
        self._node_fast_cache = (now, addr)
        return addr

    def _node_put_batch(self, batch: pb.PutObjectBatchRequest):
        """Flush a put batch over the node's fastpath plane when
        available (the gRPC stack's per-call CPU was visible in the
        large-put path); gRPC remains the fallback. Puts are idempotent
        (immutable content at a fixed id), so retrying an ambiguous
        fastpath failure over gRPC is safe here."""
        from ray_tpu._private import fastpath

        status, reply = fastpath.call_proto(
            self._node_fast_address(), fastpath.KIND_PUT_BATCH, batch,
            pb.PutObjectBatchReply, timeout=60)
        if status == "ok":
            return reply
        return self.node.PutObjectBatch(batch)

    def _enqueue_put(self, item: tuple) -> None:
        with self._put_cv:
            self._put_q.append(item + (time.monotonic() + 60.0,))
            if not self._put_flusher_started:
                self._put_flusher_started = True
                threading.Thread(target=self._put_flush_loop, daemon=True,
                                 name="put-flush").start()
            # Notify only on the empty->nonempty edge: a notify per put
            # woke the flusher thousands of times per second, and that GIL
            # churn was visible in the put() caller's own latency.
            if len(self._put_q) == 1:
                self._put_cv.notify()

    def _put_flush_loop(self) -> None:
        while not self._shutdown:
            with self._put_cv:
                while not self._put_q and not self._shutdown:
                    self._put_cv.wait(0.5)
            # Brief coalesce window: puts arrive in bursts; one batched
            # RPC for hundreds beats many for a few.
            time.sleep(0.001)
            with self._put_cv:
                # Cap by count AND bytes: the no-shm fallback carries full
                # payloads inline, and an unbounded batch could exceed the
                # gRPC message limit and fail deterministically forever.
                items, n, nbytes = [], 0, 0
                while self._put_q and n < 1024 and nbytes < (64 << 20):
                    it = self._put_q.popleft()
                    items.append(it)
                    n += 1
                    if it[0] == "data":
                        nbytes += len(it[2])
            if not items:
                continue
            batch = pb.PutObjectBatchRequest()
            now = time.monotonic()
            retry = []
            for it in items:
                oid = it[1]
                # Freed before the flush landed (local zero deletes the
                # memory copy): registering a location now would resurrect
                # a freed object and leak its store copy.
                if not self.memory.contains(oid):
                    if it[0] == "shm":
                        from ray_tpu._private.shm import ShmClient

                        ShmClient.unlink_segment(it[2])
                    continue
                if it[0] == "shm":
                    batch.items.append(pb.PutObjectRequest(
                        object_id=oid.binary(), shm_name=it[2], size=it[3],
                        owner=self.worker_id))
                else:
                    batch.items.append(pb.PutObjectRequest(
                        object_id=oid.binary(), data=it[2],
                        owner=self.worker_id))
                retry.append(it)
            if not batch.items:
                continue
            try:
                reply = self._node_put_batch(batch)
                # Items the store REJECTED (full even after spilling) have
                # no location and — for shm items — no segment anymore
                # (the node unlinks what it can't index). Re-enqueue from
                # the live in-process value so the flush retries once the
                # spiller catches up; the 60s deadline still bounds it.
                any_rejected = False
                for it, rej in zip(retry, list(reply.rejected)):
                    if not rej:
                        continue
                    any_rejected = True
                    if it[-1] <= time.monotonic():
                        logger.error(
                            "store rejected put of %s repeatedly; the "
                            "object exists only in this process",
                            it[1].hex()[:12])
                        continue
                    if it[0] == "shm":
                        self._requeue_rejected_shm(it)
                    else:
                        with self._put_cv:
                            self._put_q.append(it)
                if any_rejected:
                    # Back off before re-sending: without it the requeue
                    # spins at the coalesce interval, re-serializing and
                    # re-creating segments the node promptly rejects.
                    time.sleep(0.2)
            except Exception:  # noqa: BLE001
                self._refresh_local_node()
                kept = [it for it in retry if it[-1] > now]
                if len(kept) < len(retry):
                    logger.error(
                        "put flush failed for 60s for %d objects; they "
                        "exist only in this process and remote readers "
                        "cannot fetch them", len(retry) - len(kept))
                    for it in retry:
                        # Expired shm segments were never registered with
                        # any store: unlink or they leak in /dev/shm.
                        if it[-1] <= now and it[0] == "shm":
                            from ray_tpu._private.shm import ShmClient

                            ShmClient.unlink_segment(it[2])
                with self._put_cv:
                    self._put_q.extendleft(reversed(kept))
                time.sleep(0.2)

    def _next_put_index(self) -> int:
        with self._put_lock:
            self._put_index += 1
            return self._put_index

    def _fetch_object(self, ref: ObjectRef, deadline=None):
        """Try all known locations once. Returns (found, value, freed,
        pending) — ``freed`` means the GCS refcount hit zero and the object
        is gone for good (borrowers surface ObjectLostError instead of
        spinning); ``pending`` means another thread's pull of this object
        is in flight, so a miss must NOT trigger lineage reconstruction."""
        oid = ref.id()
        freed = False
        try:
            reply = self.node.GetObject(
                pb.GetObjectRequest(object_id=oid.binary()))
        except Exception:  # noqa: BLE001  — local raylet gone
            self._refresh_local_node()
            reply = pb.GetObjectReply(found=False)
        if reply.found:
            value = read_object_reply(reply)
            if value is not None or not reply.shm_name:
                self.memory.put(oid, value)
                return True, value, freed, False
        candidates = []
        size = 0
        if ref.owner_address() and ref.owner_address() != self.node_address:
            candidates.append(ref.owner_address())
        try:
            locs = self.gcs.GetObjectLocations(
                pb.GetObjectLocationsRequest(object_id=oid.binary()))
            freed = locs.freed
            size = int(locs.size)
            nodes = {n.node_id: n.address
                     for n in self.gcs.GetNodes(pb.GetNodesRequest()).nodes
                     if n.alive}
            candidates.extend(nodes[nid] for nid in locs.node_ids
                              if nid in nodes)
        except Exception:  # noqa: BLE001
            pass
        if not candidates:
            return False, None, freed, False
        # Pull admission (reference C13 PullManager, pull_manager.h:53):
        # bound in-flight pull bytes and dedup concurrent pulls of the
        # same object within this process. All waits are clipped to the
        # caller's remaining deadline.
        def remaining(cap):
            if deadline is None:
                return cap
            return max(0.0, min(cap, deadline - time.monotonic()))

        waited = self._pulls.begin(oid.binary(), size,
                                   wait_s=remaining(60.0))
        if waited is not None:
            waited.wait(timeout=remaining(120.0))
            hit = self.memory.get_if_ready(oid, default=None)
            if hit is not None or self.memory.contains(oid):
                return True, hit, freed, False
            waited = self._pulls.begin(oid.binary(), size,
                                       wait_s=remaining(5.0))
            if waited is not None:
                # Still contended; let the in-flight pull finish — the
                # caller's retry loop re-checks shortly.
                return False, None, freed, True
        try:
            for addr in dict.fromkeys(candidates):
                try:
                    stub = rpc.get_stub("NodeService", addr)
                    chunks = stub.PullObject(
                        pb.PullObjectRequest(object_id=oid.binary()))
                    buf = bytearray()
                    found = False
                    for chunk in chunks:
                        if not chunk.found:
                            break
                        found = True
                        buf.extend(chunk.data)
                        if chunk.eof:
                            break
                    if found:
                        value = loads_store(bytes(buf))
                        self.memory.put(oid, value)
                        try:  # cache on this node for future consumers
                            put_bytes_to_node(self.node, oid.binary(),
                                              bytes(buf), self.worker_id)
                        except Exception:  # noqa: BLE001
                            pass
                        return True, value, freed, False
                except Exception:  # noqa: BLE001
                    continue
            return False, None, freed, False
        finally:
            self._pulls.end(oid.binary(), size)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = []
        for ref in refs:
            value = self._get_one(ref, deadline)
            if isinstance(value, exceptions.RayTaskError):
                raise value.as_instanceof_cause()
            if isinstance(value, exceptions.RayTpuError):
                raise value
            out.append(value)
        return out

    def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        oid = ref.id()
        backoff = 0.002
        rebuilds = 0
        while True:
            try:
                return self.memory.get_if_ready(oid)
            except KeyError:
                pass
            # In-flight local task: its result lands via the push reply —
            # wait on the completion event instead of probing the store
            # and directory (3 RPCs per spin; the r3 roundtrip bottleneck).
            ev = self._pending_event(oid.binary())
            if ev is not None:
                if deadline is None:
                    ev.wait(5.0)
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise exceptions.GetTimeoutError(
                            f"Timed out getting object {oid.hex()}")
                    ev.wait(min(remaining, 5.0))
                continue
            found, value, freed, pending = self._fetch_object(ref, deadline)
            if found:
                return value
            if not pending and rebuilds < 3 and self._maybe_reconstruct(ref):
                rebuilds += 1
                continue
            if freed:
                # The GCS freed this object (all holders dropped, or its
                # owner was reaped) and this process can't rebuild it: a
                # typed terminal error, not a timeout (reference:
                # ObjectNotFound/OwnerDied semantics, common/status.h).
                with self._lineage_lock:
                    has_lineage = oid.binary() in self._lineage
                if not has_lineage:
                    raise exceptions.ObjectLostError(
                        f"Object {oid.hex()} was freed cluster-wide (its "
                        f"reference count reached zero or its owner died) "
                        f"and cannot be reconstructed by this process.")
            if deadline is not None and time.monotonic() >= deadline:
                raise exceptions.GetTimeoutError(
                    f"Timed out getting object {oid.hex()}")
            # Event-driven wait: OBJECT_LOC pubsub events and local result
            # arrivals notify the condition; the timeout is only a safety
            # net for events published before our subscription attached.
            with self._ready_cond:
                if not self.memory.contains(oid):
                    remaining = (None if deadline is None
                                 else max(0.0, deadline - time.monotonic()))
                    step = backoff if remaining is None \
                        else min(backoff, remaining)
                    self._ready_cond.wait(step)
            backoff = min(backoff * 2, 0.5)

    def _maybe_reconstruct(self, ref: ObjectRef, depth: int = 0) -> bool:
        """Re-execute the task that created a produced-then-lost object.

        Reference: lineage reconstruction — TaskManager::ResubmitTask
        (task_manager.h:274) + ObjectRecoveryManager. Owner-side only: this
        process must hold the creating TaskSpec (pinned while its refs live).
        Returns True when a reconstruction ran (caller retries the fetch).
        """
        oid = ref.id().binary()
        with self._lineage_lock:
            spec = self._lineage.get(oid)
        if spec is None or depth > 10:
            return False
        task_key = bytes(spec.task_id)
        # Only reconstruct objects whose producing task already completed —
        # a miss on a still-running task's return just means "pending".
        if task_key not in self._task_done:
            return False
        with self._lineage_lock:
            ev = self._reconstructing.get(task_key)
            leader = ev is None
            if leader:
                ev = self._reconstructing[task_key] = threading.Event()
        if not leader:
            ev.wait(300)
            return True
        try:
            # Task completion can be observed before the worker's location
            # update lands in the GCS directory; re-probe briefly before
            # paying for a re-execution (spurious-"lost" window). The
            # in-process store counts too: inline results land there.
            for _ in range(3):
                if self.memory.contains(ref.id()) or \
                        self._fetch_object(ref)[0]:
                    return True
                time.sleep(0.05)
            logger.warning("all copies of %s lost; re-executing task %s (%s)",
                           ref.id().hex()[:12], task_key.hex()[:12], spec.name)
            # A promoted payload's only store copy may have died with its
            # node: re-put from the lineage-retained bytes so the executor's
            # fetch can't dead-end (the inline-payload path never had this
            # failure mode).
            raw_payload = spec.payload
            if spec.payload_ref:
                raw_payload = self._lineage_payload_bytes.get(task_key, b"")
                if raw_payload and not self._is_ready(
                        ObjectRef(ObjectID(bytes(spec.payload_ref)),
                                  skip_ref_count=True)):
                    try:
                        put_bytes_to_node(self.node, bytes(spec.payload_ref),
                                          raw_payload, self.worker_id)
                    except Exception:  # noqa: BLE001
                        logger.exception("payload re-put failed for task %s",
                                         task_key.hex()[:12])
            # Recursively ensure this task's own ObjectRef args exist.
            if depth < 10:
                try:
                    (_, args, kwargs), _ = loads_payload(raw_payload)
                    for a in list(args) + list(kwargs.values()):
                        if isinstance(a, ObjectRef) and \
                                not self._fetch_object(a)[0]:
                            self._maybe_reconstruct(a, depth + 1)
                except Exception:  # noqa: BLE001
                    pass
            return_ids = [ObjectID(b) for b in spec.return_ids]
            self._lease_and_push(spec, return_ids, int(spec.max_retries))
            return True
        finally:
            ev.set()
            with self._lineage_lock:
                self._reconstructing.pop(task_key, None)

    def _is_ready(self, ref: ObjectRef) -> bool:
        """Readiness by metadata only — never fetches object data
        (the reference's Wait checks the store/directory, not contents)."""
        oid = ref.id()
        if self.memory.contains(oid):
            return True
        try:
            reply = self.node.GetObject(pb.GetObjectRequest(
                object_id=oid.binary(), metadata_only=True))
            if reply.found:
                return True
        except Exception:  # noqa: BLE001
            self._refresh_local_node()
        try:
            locs = self.gcs.GetObjectLocations(
                pb.GetObjectLocationsRequest(object_id=oid.binary()))
            return bool(locs.node_ids)
        except Exception:  # noqa: BLE001
            return False

    def _batch_ready(self, refs: List[ObjectRef]) -> List[ObjectRef]:
        """Readiness for many refs in O(1) RPCs: in-process store first,
        then one batched probe against the local node, then one batched
        directory probe at the GCS (weak #6 r2: the per-ref probe loop was
        O(refs) RPCs per wait tick, which cannot survive 10k-ref waits)."""
        ready: List[ObjectRef] = []
        rest: List[ObjectRef] = []
        for r in refs:
            (ready if self.memory.contains(r.id()) else rest).append(r)
        if not rest:
            return ready
        node_found = None
        try:
            reply = self.node.GetObjectsMeta(pb.GetObjectsMetaRequest(
                object_ids=[r.id().binary() for r in rest]))
            node_found = list(reply.found)
        except Exception:  # noqa: BLE001
            self._refresh_local_node()
        still: List[ObjectRef] = []
        if node_found is not None and len(node_found) == len(rest):
            for r, f in zip(rest, node_found):
                (ready if f else still).append(r)
        else:
            still = rest
        if still:
            try:
                reply = self.gcs.GetObjectsLocations(
                    pb.GetObjectsMetaRequest(
                        object_ids=[r.id().binary() for r in still]))
                ready.extend(r for r, f in zip(still, reply.found) if f)
            except Exception:  # noqa: BLE001
                ready.extend(r for r in still if self._is_ready(r))
        return ready

    def wait(self, refs, num_returns, timeout, fetch_local):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready_ids = set()
        fetching = set()
        while True:
            pending = [r for r in refs if r.id() not in ready_ids]
            # Locally in-flight tasks complete via push replies into the
            # memory store: checking them there (no RPC) keeps a 1k-task
            # fan-in wait from probing the node/GCS for every ref per tick.
            local_ready = []
            probe = []
            with self._pending_res_lock:
                for r in pending:
                    oid = r.id()
                    # Completed local tasks leave _pending_results but
                    # their value IS in the memory store — check it first
                    # or a fan-in wait pays a GCS probe per completed ref.
                    if self.memory.contains(oid):
                        local_ready.append(r)
                    elif oid.binary() not in self._pending_results:
                        probe.append(r)
            for ref in local_ready + self._batch_ready(probe):
                if len(ready_ids) >= num_returns:
                    break  # caller asked for N: don't fetch the surplus
                ready_ids.add(ref.id())
                if fetch_local and not self.memory.contains(ref.id()) \
                        and ref.id() not in fetching:
                    fetching.add(ref.id())
                    self._pool.submit(self._fetch_object, ref)
            if len(ready_ids) >= num_returns or (
                    deadline is not None and time.monotonic() >= deadline):
                ready = [r for r in refs if r.id() in ready_ids]
                not_ready = [r for r in refs if r.id() not in ready_ids]
                return ready, not_ready
            with self._ready_cond:
                self._ready_cond.wait(0.05)

    def free(self, refs):
        ids = [r.id().binary() for r in refs]
        self.memory.delete([r.id() for r in refs])
        for ob in ids:
            self._lazy_results.pop(ob, None)
        try:
            for n in self.gcs.GetNodes(pb.GetNodesRequest()).nodes:
                if n.alive:
                    rpc.get_stub("NodeService", n.address).FreeObjects(
                        pb.FreeObjectsRequest(object_ids=ids))
        except Exception:  # noqa: BLE001
            pass

    # ---------------------------------------------------------------- tasks
    def submit_task(self, function, function_name, args, kwargs, options):
        mdefs.TASKS_SUBMITTED.inc(tags={"kind": "task"})
        task_id = TaskID.for_normal_task(self.job_id)
        streaming = is_streaming(options.num_returns)
        nreturns = 1 if streaming else max(options.num_returns, 1)
        return_ids = [ObjectID.from_task(task_id, i) for i in range(nreturns)]
        payload, contained = dumps_payload((function, args, kwargs))
        spec = pb.TaskSpec(
            task_id=task_id.binary(),
            name=function_name,
            return_ids=[oid.binary() for oid in return_ids],
            max_retries=options.max_retries or 0,
            returns_stream=streaming,
        )
        payload_oid = self._maybe_promote_payload(task_id, payload, spec)
        if options.runtime_env:
            spec.runtime_env = pickle.dumps(
                self._prepare_runtime_env(options.runtime_env))
        for k, v in options.task_resources().items():
            spec.resources[k] = v
        from ray_tpu._private.options import resolve_placement

        pf = resolve_placement(options)
        if pf.placement_group_id:
            spec.placement_group_id = pf.placement_group_id
            spec.pg_bundle_index = pf.bundle_index
            spec.pg_capture_child_tasks = pf.capture_child_tasks
        if pf.affinity_node_id:
            spec.affinity_node_id = pf.affinity_node_id
            spec.affinity_soft = pf.affinity_soft
        if pf.strategy:
            spec.strategy = pf.strategy
        if pf.label_selector:
            spec.label_selector = pf.label_selector
        from ray_tpu.util import tracing

        if tracing.enabled():
            tracing.inject_context(spec)
        # Pin every contained ObjectRef (top-level AND nested in containers)
        # for the task's flight time so its refcount can't hit zero between
        # submit and the worker's borrow flush. A promoted payload gets the
        # same flight pin on top of its lineage pin below.
        pinned = list(contained)
        self._flush_escaped(contained)
        if payload_oid is not None:
            pinned.append(payload_oid)
            self.refs.incr(payload_oid)  # lineage pin (see _on_ref_zero)
        for oid in pinned:
            self.refs.incr(oid)
        # Pin lineage for the returns (dropped when this owner's local refs
        # to them reach zero — see _on_ref_zero). A promoted payload stays
        # pinned as long as the lineage lives so reconstruction can re-ship
        # nothing (lineage pinning, task_manager.h:274).
        with self._lineage_lock:
            for oid in return_ids:
                self._lineage[oid.binary()] = spec
            self._task_lineage_count[task_id.binary()] = \
                self._task_lineage_count.get(task_id.binary(), 0) + nreturns
            if payload_oid is not None:
                self._lineage_payload_bytes[task_id.binary()] = payload
        self._register_pending(return_ids)
        # Child registry for recursive cancellation: a task submitted
        # while another task executes on this runtime is that task's
        # child (reference: recursive CancelTask walks the task graph).
        from ray_tpu._private.runtime.local import current_task_context

        pctx = current_task_context()
        if pctx is not None and pctx.task_id is not None:
            with self._cancel_lock:
                self._children.setdefault(pctx.task_id.binary(), []).append(
                    (task_id.binary(), [o.binary() for o in return_ids]))
        # Submitter-side dependency resolution (reference:
        # ``dependency_resolver.h`` — a task is not dispatched until its
        # direct ObjectRef args exist). Without this, dependent tasks
        # occupy leased workers blocking on get(): a two-stage shuffle
        # whose reduce tasks grab every worker before any map task runs
        # deadlocks the pool.
        direct_deps = [a for a in args if isinstance(a, ObjectRef)]
        direct_deps += [v for v in kwargs.values()
                        if isinstance(v, ObjectRef)]
        unready = [r for r in direct_deps if not self._dep_ready_fast(r)]
        if unready:
            self._pool.submit(self._wait_deps_then_dispatch, unready, spec,
                              return_ids, options.max_retries or 0, pinned,
                              direct_deps)
        else:
            self._apply_locality_hint(spec, direct_deps)
            self._dispatch_task(spec, return_ids, options.max_retries or 0,
                                pinned)
        return [ObjectRef(oid, owner_address=self.node_address)
                for oid in return_ids]

    # Locality-aware lease targeting (reference:
    # ``LocalityAwareLeasePolicy``, ``core_worker/lease_policy.h:58``):
    # only argument payloads at least this large steer the lease — below
    # it the chunked pull costs less than giving up lease reuse.
    LOCALITY_MIN_BYTES = 100 * 1024
    LOCALITY_CACHE_TTL_S = 5.0

    def _dep_locations(self, oid: ObjectID):
        """(size, node_ids) via the GCS directory, TTL-cached: a fan-out
        of N tasks sharing one big arg must not pay N directory RPCs
        (same concern as the _node_addresses cache)."""
        key = oid.binary()
        now = time.monotonic()
        hit = self._loc_cache.get(key)
        if hit is not None and now - hit[0] < self.LOCALITY_CACHE_TTL_S:
            return hit[1], hit[2]
        try:
            locs = self.gcs.GetObjectLocations(
                pb.GetObjectLocationsRequest(object_id=key))
        except Exception:  # noqa: BLE001 — directory miss: no hint
            return 0, ()
        size = 0 if locs.freed else locs.size
        node_ids = tuple(locs.node_ids)
        if len(self._loc_cache) > 4096:
            self._loc_cache.clear()
        self._loc_cache[key] = (now, size, node_ids)
        return size, node_ids

    def _apply_locality_hint(self, spec: pb.TaskSpec,
                             deps: List[ObjectRef]) -> None:
        """Prefer leasing on the node holding the most resident argument
        bytes: a task whose 1GB arg lives on node B should run on node B
        instead of paying a cross-node chunked pull (on a TPU pod: DCN
        traffic vs none). Expressed as SOFT node affinity so the existing
        spillback machinery handles a busy/full target."""
        if (spec.placement_group_id or spec.affinity_node_id
                or spec.strategy or spec.label_selector or not deps):
            return
        per_node: Dict[str, int] = {}
        local_bytes = 0
        for ref in deps[:4]:  # bounded directory cost per submit
            oid = ref.id()
            if self.memory.contains(oid):
                continue  # value already in-process: no pull either way
            size, node_ids = self._dep_locations(oid)
            if not size:
                continue
            for nid in node_ids:
                if nid == self.node_id:
                    local_bytes += size
                else:
                    per_node[nid] = per_node.get(nid, 0) + size
        if not per_node:
            return
        best, best_bytes = max(per_node.items(), key=lambda kv: kv[1])
        if best_bytes >= self.LOCALITY_MIN_BYTES and \
                best_bytes > local_bytes:
            spec.affinity_node_id = best
            spec.affinity_soft = True

    def _dep_ready_fast(self, ref: ObjectRef) -> bool:
        """RPC-free readiness check for the submit hot path: only an
        in-process value is known-ready without an RPC; everything else
        routes through the async dependency waiter (which batch-probes
        the directory for refs owned elsewhere)."""
        return self.memory.contains(ref.id())

    def _wait_deps_then_dispatch(self, deps: List[ObjectRef],
                                 spec: pb.TaskSpec,
                                 return_ids: List[ObjectID], retries: int,
                                 pinned: Optional[List[bytes]],
                                 all_deps: Optional[List[ObjectRef]] = None,
                                 ) -> None:
        """Block (off the lease path — no worker is held) until every
        direct dependency exists somewhere, then dispatch. The deadline
        matches the executor-side arg-fetch timeout: on expiry the task
        dispatches anyway and surfaces the fetch error through the normal
        path."""
        deadline = time.monotonic() + 300.0
        while not self._shutdown and time.monotonic() < deadline:
            if self._task_cancelled(bytes(spec.task_id)):
                self._store_cancelled(spec, return_ids)
                for oid in pinned or ():
                    self.refs.decr(oid)
                return
            unready: List[ObjectRef] = []
            probe: List[ObjectRef] = []
            for ref in deps:
                oid = ref.id()
                if self.memory.contains(oid):
                    continue
                with self._pending_res_lock:
                    if oid.binary() in self._pending_results:
                        unready.append(ref)
                        continue
                probe.append(ref)
            if probe:
                ready = {r.id() for r in self._batch_ready(probe)}
                unready.extend(r for r in probe if r.id() not in ready)
            if not unready:
                break
            deps = unready
            with self._ready_cond:
                self._ready_cond.wait(0.05)
        # Hint AFTER deps exist: locations are only known once produced.
        self._apply_locality_hint(spec, all_deps or deps)
        self._dispatch_task(spec, return_ids, retries, pinned)

    def _register_pending(self, return_ids: List[ObjectID]) -> None:
        """Mark a local task's returns as in-flight: getters/waiters block
        on the completion event instead of probing the store/directory.
        The Event is allocated LAZILY by the first getter that actually
        waits (``_pending_event``) — most tasks complete before anyone
        blocks, and an Event (condition + two locks) per task was
        measurable on the submit hot path."""
        with self._pending_res_lock:
            for oid in return_ids:
                self._pending_results[oid.binary()] = None

    def _pending_event(self, oid_bin: bytes) -> Optional[threading.Event]:
        """The in-flight completion event for an object, created on first
        waiter; None when the task is not in flight locally."""
        with self._pending_res_lock:
            if oid_bin not in self._pending_results:
                return None
            ev = self._pending_results[oid_bin]
            if ev is None:
                ev = self._pending_results[oid_bin] = threading.Event()
            return ev

    def _complete_pending(self, return_ids) -> None:
        cbs: List = []
        with self._pending_res_lock:
            evs = set()
            for oid in return_ids:
                ob = oid.binary() if hasattr(oid, "binary") else oid
                evs.add(self._pending_results.pop(ob, None))
                cbs.extend(self._pending_callbacks.pop(ob, ()))
        for ev in evs:
            if ev is not None:
                ev.set()
        for cb in cbs:
            # Dispatch off this thread: it holds a _completion_slots
            # permit, and resolving a future runs user done-callbacks —
            # a blocking callback (e.g. a get() continuation) inline
            # here could hold every slot and deadlock task completion.
            try:
                self._pool.submit(_run_callback, cb)
            except RuntimeError:  # pool closed mid-shutdown
                _run_callback(cb)

    PAYLOAD_PROMOTE_BYTES = 100 * 1024  # reference: >100KB args to plasma
    PAYLOAD_INDEX = (1 << 30) - 1       # object index reserved for payloads

    def _maybe_promote_payload(self, task_id: TaskID, payload: bytes,
                               spec: pb.TaskSpec) -> Optional[bytes]:
        """Large task payloads go to the object store and travel by ref
        (reference C29, ``core_worker.cc:1527``): retries, spillback, and
        reconstruction then re-ship an object id, not megabytes. Returns
        the payload's object id (pinned by the caller) or None when the
        payload rode inline."""
        if len(payload) <= self.PAYLOAD_PROMOTE_BYTES:
            spec.payload = payload
            return None
        oid = ObjectID.from_task(task_id, self.PAYLOAD_INDEX)
        try:
            stored = put_bytes_to_node(self.node, oid.binary(), payload,
                                       self.worker_id)
        except Exception:  # noqa: BLE001
            if not self._refresh_local_node():
                spec.payload = payload
                return None
            stored = put_bytes_to_node(self.node, oid.binary(), payload,
                                       self.worker_id)
        if not stored:
            # Store rejected the promotion (full): ship the payload inline
            # — heavier on the wire, but the task still runs.
            spec.payload = payload
            return None
        spec.payload_ref = oid.binary()
        return oid.binary()

    def fetch_object_bytes(self, oid_binary: bytes,
                           timeout: float = 120.0) -> Optional[bytes]:
        """Raw serialized bytes of a store object (payload-ref fetch path):
        local node first, then any directory location via chunked pull."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                reply = self.node.GetObject(
                    pb.GetObjectRequest(object_id=oid_binary))
                if reply.found:
                    if reply.shm_name:
                        from ray_tpu._private.shm import ShmClient

                        data = ShmClient.read_segment(reply.shm_name,
                                                      reply.size)
                        if data is not None:
                            return data
                    else:
                        return reply.data
            except Exception:  # noqa: BLE001
                self._refresh_local_node()
            try:
                locs = self.gcs.GetObjectLocations(
                    pb.GetObjectLocationsRequest(object_id=oid_binary))
                if locs.freed:
                    return None  # freed cluster-wide: no point polling on
                nodes = self._node_addresses()
                for nid in locs.node_ids:
                    addr = nodes.get(nid)
                    if not addr:
                        continue
                    stub = rpc.get_stub("NodeService", addr)
                    buf = bytearray()
                    found = False
                    for chunk in stub.PullObject(
                            pb.PullObjectRequest(object_id=oid_binary)):
                        if not chunk.found:
                            break
                        found = True
                        buf.extend(chunk.data)
                        if chunk.eof:
                            break
                    if found:
                        return bytes(buf)
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.05)
        return None

    def _prepare_runtime_env(self, renv: dict) -> dict:
        """Driver-side runtime_env prep: local working_dir/py_modules
        directories become content-addressed KV packages any node can
        materialize (reference: runtime_env/packaging.py upload path).
        Cached per identity so repeated submissions don't re-hash."""
        if not renv:
            return renv
        if not hasattr(self, "_renv_cache"):
            self._renv_cache = {}
        from ray_tpu._private import runtime_env as renv_mod
        from ray_tpu._private.runtime_env import packaging as pkg_mod

        # Key on directory fingerprints, not just paths: editing the
        # working_dir between submissions must produce a fresh package.
        prints = []
        for d in [renv.get("working_dir"), *(renv.get("py_modules") or [])]:
            if isinstance(d, str) and not pkg_mod.is_uri(d) and \
                    os.path.isdir(d):
                try:
                    prints.append(pkg_mod.dir_fingerprint(d))
                except OSError:
                    pass
        key = pickle.dumps(
            (sorted(renv.items(), key=lambda kv: kv[0]), prints))
        cached = self._renv_cache.get(key)
        if cached is None:
            cached = renv_mod.prepare(renv, self.gcs)
            self._renv_cache[key] = cached
            while len(self._renv_cache) > 256:
                self._renv_cache.pop(next(iter(self._renv_cache)))
        return cached

    def release_stream_tail(self, length_ref: ObjectRef,
                            from_index: int) -> None:
        """Free the unconsumed items of an abandoned ObjectRefGenerator.

        No holder ever registered the tail items (the consumer stopped
        iterating before reaching them), so without this they stay pinned
        in the store for the job's lifetime. Waits for the stream length,
        then emits a transient +1/-1 refcount pair per tail id — the
        existing GCS free path reclaims stored copies and directory
        entries cluster-wide (reference: ObjectRefStream deletion,
        ``task_manager.h:104``).
        """
        task_id = length_ref.task_id()

        def _reap():
            from ray_tpu._private.object_ref import STREAM_INDEX_BASE

            try:
                # Wait as long as the producer runs: the length ref always
                # resolves eventually (a value, or a stored error when the
                # task/worker dies), and bailing early would leak exactly
                # the tail this reaper exists to reclaim.
                while not self._shutdown:
                    ready, _ = self.wait([length_ref], num_returns=1,
                                         timeout=60.0, fetch_local=True)
                    if ready:
                        break
                else:
                    return
                n = int(self.get([length_ref], timeout=30)[0])
            except Exception:  # noqa: BLE001
                # Stream errored: the count never materialized, but items
                # stored before the failure still exist. Their ids are
                # contiguous, so probe until the first gap.
                n = None
            i = from_index
            while n is None or i < n:
                oid_obj = ObjectID.from_task(task_id, STREAM_INDEX_BASE + i)
                if n is None and not self._is_ready(
                        ObjectRef(oid_obj, skip_ref_count=True)):
                    break
                self.refs.incr(oid_obj.binary())
                self.refs.decr(oid_obj.binary())
                i += 1

        threading.Thread(target=_reap, daemon=True,
                         name="stream-reaper").start()

    # ------------------------------------------------------ lease caching
    # Reference: normal task submitters keep granted worker leases for a
    # short idle window and pipeline same-shaped tasks onto them
    # (``normal_task_submitter.cc:88-145``) — skipping the per-task
    # lease/return round-trip is the single biggest tasks/s lever.
    LEASE_CACHE_TTL_S = 0.2

    def _lease_signature(self, spec: pb.TaskSpec):
        """Cache key, or None when the task isn't lease-reusable (PG- or
        affinity-targeted leases are placement-specific)."""
        if spec.placement_group_id or spec.affinity_node_id:
            return None
        if spec.strategy == "SPREAD":
            # Lease reuse would serialize a fan-out onto one node — the
            # opposite of what SPREAD promises. Always negotiate.
            return None
        return (tuple(sorted(spec.resources.items())),
                bytes(spec.runtime_env), bytes(spec.label_selector))

    def _take_cached_lease(self, sig) -> Optional[dict]:
        with self._lease_cache_lock:
            lst = self._lease_cache.get(sig)
            if lst:
                lease = lst.pop()
            else:
                lease = None
        mdefs.LEASE_CACHE.inc(tags={
            "outcome": "hit" if lease is not None else "miss"})
        return lease

    def _cache_lease(self, sig, lease: dict) -> bool:
        lease["ts"] = time.monotonic()
        with self._lease_cache_lock:
            if self._shutdown:
                return False
            self._lease_cache.setdefault(sig, []).append(lease)
            self._lease_reaper_started or self._start_lease_reaper()
            return True

    def _start_lease_reaper(self) -> bool:
        self._lease_reaper_started = True
        threading.Thread(target=self._lease_reaper_loop, daemon=True,
                         name="lease-reaper").start()
        return True

    def _lease_reaper_loop(self):
        while not self._shutdown:
            time.sleep(0.05)
            now = time.monotonic()
            expired = []
            with self._lease_cache_lock:
                for sig, lst in list(self._lease_cache.items()):
                    keep = [l for l in lst
                            if now - l["ts"] <= self.LEASE_CACHE_TTL_S]
                    expired.extend(l for l in lst if l not in keep)
                    if keep:
                        self._lease_cache[sig] = keep
                    else:
                        self._lease_cache.pop(sig, None)
            for lease in expired:
                self._return_lease(lease)

    def _return_lease(self, lease: dict) -> None:
        try:
            lease["node"].ReturnWorker(pb.ReturnWorkerRequest(
                worker_id=lease["worker_id"]))
        except Exception:  # noqa: BLE001
            pass

    def _drain_lease_cache(self) -> None:
        with self._lease_cache_lock:
            leases = [l for lst in self._lease_cache.values() for l in lst]
            self._lease_cache.clear()
        for lease in leases:
            self._return_lease(lease)

    # ------------------------------------------------- lease-runner queues
    # Reference: the NormalTaskSubmitter pipelines same-shaped tasks onto
    # held worker leases (``normal_task_submitter.cc:88-145``). One queue
    # per lease signature; a bounded set of runner threads each hold one
    # lease and drain the queue — a 1,000-task fan-out pays a handful of
    # lease negotiations, not 1,000 (the r3 tasks/s bottleneck: every task
    # paid lease RPCs because independent submitter threads camped at the
    # node manager and starved the lease cache).
    MAX_SIG_RUNNERS = int(os.environ.get("RAY_TPU_SIG_RUNNERS", 16))

    def _dispatch_task(self, spec: pb.TaskSpec, return_ids: List[ObjectID],
                       retries: int, pinned: Optional[List[bytes]] = None):
        sig = self._lease_signature(spec)
        if sig is None:
            # Placement-specific lease (PG/affinity/SPREAD): dedicated
            # negotiation per task, off the shared queue.
            self._pool.submit(self._lease_and_push, spec, return_ids,
                              retries, pinned)
            return
        item = [spec, return_ids, retries, pinned, 0]
        with self._sig_lock:
            st = self._sig_queues.get(sig)
            if st is None:
                st = self._sig_queues[sig] = {"items": [], "runners": 0}
            st["items"].append(item)
            spawn = st["runners"] < self.MAX_SIG_RUNNERS
            if spawn:
                st["runners"] += 1
        if spawn:
            self._pool.submit(self._sig_runner_loop, sig, st)

    # Tasks drained per lease iteration: one fastpath frame + one executor
    # hop carries up to this many sub-millisecond tasks (per-push RPC and
    # thread overhead dominated the r4 task-throughput profile).
    SIG_PUSH_BATCH = 16

    def _sig_runner_loop(self, sig, st: dict) -> None:
        lease = None
        lease_cached = False  # a stale cached lease must not burn attempts
        while True:
            with self._sig_lock:
                if self._shutdown or not st["items"]:
                    # Exit check and runner decrement are atomic with the
                    # enqueue path: an item appended before this point is
                    # visible; one appended after sees runners already
                    # decremented and spawns a fresh runner.
                    st["runners"] -= 1
                    break
                items = st["items"][:self.SIG_PUSH_BATCH]
                del st["items"][:len(items)]
            live = []
            for item in items:
                if self._task_cancelled(bytes(item[0].task_id)):
                    self._store_cancelled(item[0], item[1])
                    self._finish_item(item)
                else:
                    live.append(item)
            if not live:
                continue
            spec, return_ids = live[0][0], live[0][1]
            try:
                if lease is None:
                    lease = self._take_cached_lease(sig)
                    lease_cached = lease is not None
                if lease is None:
                    lease = self._negotiate_lease(spec, sig)
                    lease_cached = False
                    if lease is None:  # aborted: a cached lease appeared
                        with self._sig_lock:
                            st["items"][:0] = live
                        continue
                if self._push_batch_on_lease(live, lease):
                    continue
                # Worker died mid-push (or stale cached lease).
                self._return_lease(lease)
                lease = None
                requeue = []
                for item in live:
                    if not lease_cached:
                        item[4] += 1
                    if item[4] <= max(item[2], 3):
                        requeue.append(item)
                    else:
                        self._store_error(
                            exceptions.RayTaskError(
                                item[0].name,
                                f"Worker executing {item[0].name} died"),
                            item[1])
                        self._finish_item(item)
                if requeue:
                    with self._sig_lock:
                        st["items"][:0] = requeue
            except exceptions.TaskCancelledError:
                # Negotiation observed live[0]'s cancel; the rest requeue.
                self._store_cancelled(spec, return_ids)  # typed + flag drop
                self._finish_item(live[0])
                if len(live) > 1:
                    with self._sig_lock:
                        st["items"][:0] = live[1:]
            except BaseException as e:  # noqa: BLE001
                for item in live:
                    self._store_error(
                        exceptions.RayTaskError.from_exception(
                            e, item[0].name), item[1])
                    self._finish_item(item)
        if lease is not None and not self._cache_lease(sig, lease):
            self._return_lease(lease)

    def _push_batch_on_lease(self, items: List[list], lease: dict) -> bool:
        """Push a chunk of same-signature tasks to one leased worker.
        Returns False when the worker died (callers apply the retry
        policy to every item); on success every item's results are
        applied and its pins released."""
        if len(items) == 1:
            item = items[0]
            if self._push_on_lease(item[0], item[1], lease):
                self._finish_item(item)
                return True
            return False
        from ray_tpu._private import fastpath

        breq = pb.PushTaskBatchRequest()
        for item in items:
            spec = item[0]
            del spec.tpu_chips[:]
            spec.tpu_chips.extend(lease["tpu_chips"])
            breq.specs.append(spec)
            self._running_locs[bytes(spec.task_id)] = \
                lease["worker_address"]
        push_start = time.monotonic()
        try:
            status, reply = fastpath.call_proto(
                lease.get("fast_address", ""), fastpath.KIND_PUSH_BATCH,
                breq, pb.PushTaskBatchReply, timeout=PUSH_TIMEOUT_S + 5)
            if status == "error":
                # Connection died mid-call: the batch MAY have executed;
                # do NOT resend over gRPC — route through the retry gate.
                return False
            if status == "no_client":
                stub = rpc.get_stub("WorkerService", lease["worker_address"])
                try:
                    reply = stub.PushTaskBatch(breq,
                                               timeout=PUSH_TIMEOUT_S)
                except Exception:  # noqa: BLE001
                    return False
        finally:
            for item in items:
                self._running_locs.pop(bytes(item[0].task_id), None)
        if len(reply.results) != len(items):
            # Short (or over-long) reply: zipping it against items would
            # silently drop the tail — those tasks would never complete
            # and their flight pins would never release. Treat it like a
            # dead worker so every item goes through the retry/error
            # gate (which always releases pins).
            logger.warning(
                "batch push returned %d results for %d tasks; routing "
                "the batch through the retry path",
                len(reply.results), len(items))
            return False
        mdefs.PUSH_LATENCY.observe(time.monotonic() - push_start,
                                   tags={"mode": "batch"})
        with self._completion_slots:
            for item, result in zip(items, reply.results):
                self._apply_push_result(result, item[1], item[0].name)
                self._finish_item(item)
        if self._cancelled_tasks:
            with self._cancel_lock:
                for item in items:
                    self._cancelled_tasks.discard(bytes(item[0].task_id))
        return True

    def _finish_item(self, item) -> None:
        """Release an item's flight-time pins exactly once."""
        pinned, item[3] = item[3], None
        for oid in pinned or ():
            self.refs.decr(oid)

    def _lease_and_push(self, spec: pb.TaskSpec, return_ids: List[ObjectID],
                        retries: int, pinned: Optional[List[bytes]] = None):
        try:
            attempt = 0
            while True:
                if self._task_cancelled(bytes(spec.task_id)):
                    self._store_cancelled(spec, return_ids)
                    return
                try:
                    self._lease_and_push_once(spec, return_ids)
                    return
                except exceptions.WorkerCrashedError as e:
                    # System failures retry by default (reference semantics).
                    if attempt < max(retries, 3):
                        attempt += 1
                        time.sleep(0.05)
                        continue
                    self._store_error(
                        exceptions.RayTaskError(spec.name, str(e)), return_ids)
                    return
        except exceptions.TaskCancelledError:
            self._store_cancelled(spec, return_ids)  # typed + flag drop
        except BaseException as e:  # noqa: BLE001
            self._store_error(
                exceptions.RayTaskError.from_exception(e, spec.name),
                return_ids)
        finally:
            for oid in pinned or ():
                self.refs.decr(oid)

    def _node_address(self, node_id: str) -> Optional[str]:
        return self._node_addresses().get(node_id)

    NODE_ADDR_TTL_S = 1.0

    def _node_addresses(self) -> Dict[str, str]:
        # Cached briefly: SPREAD round-robin consults this per submission,
        # and a per-task GetNodes would make the GCS the throughput
        # bottleneck for exactly the short-task fan-outs SPREAD serves.
        # Staleness is tolerated by the spillback/retry paths.
        now = time.monotonic()
        cached = self._node_addr_cache
        if cached is not None and now - cached[0] < self.NODE_ADDR_TTL_S:
            return cached[1]
        addrs = {n.node_id: n.address
                 for n in self.gcs.GetNodes(pb.GetNodesRequest()).nodes
                 if n.alive}
        self._node_addr_cache = (now, addrs)
        return addrs

    def _pg_lease_targets(self, spec: pb.TaskSpec) -> List[Any]:
        """Node stubs hosting the target bundle(s), waiting for placement
        (reference: tasks targeting a PG queue until the group is CREATED,
        gcs_placement_group_manager.h WaitPlacementGroupReady)."""
        gid = bytes(spec.placement_group_id)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            reply = self.gcs.GetPlacementGroup(
                pb.GetPlacementGroupRequest(group_id=gid))
            if not reply.found:
                raise exceptions.RayTpuError(
                    f"Task {spec.name} targets placement group "
                    f"{gid.hex()[:12]} which does not exist")
            info = reply.info
            if info.state == "REMOVED":
                raise exceptions.RayTpuError(
                    f"Task {spec.name} targets removed placement group "
                    f"{gid.hex()[:12]}")
            if info.state == "INFEASIBLE":
                raise exceptions.RayTpuError(
                    f"Placement group {gid.hex()[:12]} is infeasible; "
                    f"task {spec.name} can never be placed")
            if info.state == "CREATED":
                if spec.pg_bundle_index >= 0:
                    node_ids = [b.node_id for b in info.bundles
                                if b.index == spec.pg_bundle_index
                                and b.node_id]
                else:
                    node_ids = list(dict.fromkeys(
                        b.node_id for b in info.bundles if b.node_id))
                addrs = self._node_addresses()
                stubs = [rpc.get_stub("NodeService", addrs[nid])
                         for nid in node_ids if nid in addrs]
                if stubs:
                    return stubs
            time.sleep(0.05)
        raise exceptions.RayTpuError(
            f"Timed out waiting for placement group {gid.hex()[:12]} "
            f"to be placed (task {spec.name})")

    def _affinity_target(self, spec: pb.TaskSpec):
        addr = self._node_address(spec.affinity_node_id)
        if addr is not None:
            return rpc.get_stub("NodeService", addr)
        if spec.affinity_soft:
            return self.node
        raise exceptions.RayTpuError(
            f"Task {spec.name} has hard node affinity to "
            f"{spec.affinity_node_id[:8]} which is not alive")

    def _lease_and_push_once(self, spec: pb.TaskSpec,
                             return_ids: List[ObjectID]):
        """Submit one task: consume a cached lease when available, else
        negotiate a fresh one.

        CPU-active phases (lease negotiation, completion processing) are
        bounded by small semaphores — beyond ~8 concurrently-active
        submitters, GIL + grpc contention makes concurrent submission
        slower than sequential (measured: 150 vs 500 tasks/s) — while the
        execution wait holds neither (it sleeps in grpc with the GIL
        dropped), so in-flight task count is bounded only by the wide
        pool. The two phases use SEPARATE semaphores: lease-waiting
        submitters must never starve the completion processing that frees
        their workers.
        """
        sig = self._lease_signature(spec)
        while True:
            if sig is not None:
                lease = self._take_cached_lease(sig)
                if lease is not None:
                    if self._push_with_lease(spec, return_ids, sig, lease,
                                             fresh=False):
                        return
                    continue  # stale cached lease (worker died): retry
            lease = self._negotiate_lease(spec, sig)
            if lease is None:
                continue  # aborted to consume a newly-cached lease
            self._push_with_lease(spec, return_ids, sig, lease, fresh=True)
            return

    def _push_on_lease(self, spec: pb.TaskSpec, return_ids: List[ObjectID],
                       lease: dict) -> bool:
        """Push one task to a leased worker and apply the result. Returns
        False when the worker died (the lease is unusable; the task may or
        may not have run — callers apply the system-failure retry policy).
        The lease itself is NOT disposed here: runners keep it for the
        next queued task."""
        tid = bytes(spec.task_id)
        if self._task_cancelled(tid):
            self._store_cancelled(spec, return_ids)
            return True
        del spec.tpu_chips[:]
        spec.tpu_chips.extend(lease["tpu_chips"])
        # Visible to cancel() for the duration of the push: a CancelTask
        # RPC to this address interrupts the executor. Plain (GIL-atomic)
        # dict write — cancel() tolerates the tiny record/read race as
        # best-effort, and a lock here is per-task hot-path cost.
        self._running_locs[tid] = lease["worker_address"]
        push_start = time.monotonic()
        try:
            result = self._push_fast(lease.get("fast_address", ""), spec)
            if result is False:
                return False
            if result is None:
                stub = rpc.get_stub("WorkerService", lease["worker_address"])
                attempts = 0
                while True:
                    try:
                        fut = stub.PushTask(pb.PushTaskRequest(spec=spec),
                                            timeout=PUSH_TIMEOUT_S,
                                            wait=False)
                        result = fut.result(timeout=PUSH_TIMEOUT_S + 5)
                        break
                    except Exception as e:  # noqa: BLE001
                        # wait=False bypasses the stub's retry wrapper;
                        # re-dispatch UNAVAILABLE blips here (the call never
                        # reached the worker, so the retry is safe even for
                        # non-idempotent pushes) instead of burning a
                        # task-level attempt.
                        import grpc as _grpc

                        code = e.code() if hasattr(e, "code") else None
                        if code == _grpc.StatusCode.UNAVAILABLE \
                                and attempts < 2:
                            attempts += 1
                            time.sleep(0.05 * attempts)
                            continue
                        return False
        finally:
            self._running_locs.pop(tid, None)
        mdefs.PUSH_LATENCY.observe(time.monotonic() - push_start,
                                   tags={"mode": "single"})
        with self._completion_slots:
            self._apply_push_result(result, return_ids, spec.name)
        if self._cancelled_tasks:
            with self._cancel_lock:
                self._cancelled_tasks.discard(tid)
        return True

    def _push_with_lease(self, spec: pb.TaskSpec,
                         return_ids: List[ObjectID], sig, lease: dict,
                         fresh: bool) -> bool:
        """One-shot push for the non-queued path: disposes the lease
        (cache or return). Returns False for a stale cached lease so the
        caller falls back to a fresh one; a fresh lease's worker dying
        raises WorkerCrashedError (the retry machinery decides)."""
        if self._push_on_lease(spec, return_ids, lease):
            # Keep the lease for the reuse window instead of returning it
            # (the reaper returns it after LEASE_CACHE_TTL_S idle).
            if sig is None or not self._cache_lease(sig, lease):
                self._return_lease(lease)
            return True
        self._return_lease(lease)
        if fresh:
            raise exceptions.WorkerCrashedError(
                f"Worker executing {spec.name} died")
        return False

    def _push_fast(self, fast_address: str, spec: pb.TaskSpec):
        """Push over the fastpath task plane (framed TCP, fastpath.py).

        Returns a PushTaskResult, None when no fastpath is available
        (caller uses gRPC), or False when the connection died mid-call
        (worker gone: the task may or may not have run — same ambiguity
        as a failed gRPC push, handled by the same retry policy)."""
        if not fast_address:
            return None
        from ray_tpu._private import fastpath

        fc = fastpath.get_client(fast_address)
        if fc is None:
            return None
        try:
            data = fc.call(fastpath.KIND_PUSH_TASK,
                           pb.PushTaskRequest(spec=spec).SerializeToString(),
                           timeout=PUSH_TIMEOUT_S + 5)
        except (ConnectionError, TimeoutError):
            return False
        except Exception:  # noqa: BLE001 — Future timeout et al.
            return False
        result = pb.PushTaskResult()
        result.ParseFromString(data)
        return result

    def _next_spread_target(self):
        try:
            addrs = sorted(self._node_addresses().values())
        except Exception:  # noqa: BLE001
            return self.node
        if not addrs:
            return self.node
        with self._spread_lock:
            self._spread_idx = (self._spread_idx + 1) % len(addrs)
            addr = addrs[self._spread_idx]
        return rpc.get_stub("NodeService", addr)

    def _has_cached_lease(self, sig) -> bool:
        with self._lease_cache_lock:
            return bool(self._lease_cache.get(sig))

    def _negotiate_lease(self, spec: pb.TaskSpec, sig) -> Optional[dict]:
        """Acquire a fresh worker lease under a submit slot.

        Returns None (without a lease) when a cached lease for the same
        signature appears mid-negotiation: the caller consumes it instead.
        Without this abort the system deadlocks under fan-out — every
        worker can end up parked in the lease cache while all slot-holding
        negotiators wait for a worker to free."""
        self._submit_slots.acquire()
        slot_acquired = time.monotonic()
        lease_kind = ("pg" if spec.placement_group_id else
                      "affinity" if spec.affinity_node_id else
                      (spec.strategy or "default").lower())
        negotiate_start = slot_acquired
        try:
            pg_targets: List[Any] = []
            if spec.placement_group_id:
                pg_targets = self._pg_lease_targets(spec)
                target = pg_targets[0]
            elif spec.affinity_node_id:
                target = self._affinity_target(spec)
            elif spec.strategy == "SPREAD":
                # Round-robin the initial lease target (reference:
                # spread_scheduling_policy iterates nodes round-robin):
                # utilization alone cannot spread short tasks — each one
                # releases its resources before the next lease looks.
                target = self._next_spread_target()
            else:
                target = self.node
            deadline = time.monotonic() + 300.0
            backoff = 0.01
            spillbacks = 0
            while True:
                if self._task_cancelled(bytes(spec.task_id)):
                    raise exceptions.TaskCancelledError(
                        TaskID(bytes(spec.task_id)))
                if sig is not None and self._has_cached_lease(sig):
                    return None
                # Fairness: a capacity-starved negotiation (lease waits can
                # last minutes) must not camp on its slot and head-of-line
                # block placeable tasks — cycle the slot periodically.
                if time.monotonic() - slot_acquired > 2.0:
                    self._submit_slots.release()
                    time.sleep(0.005)
                    self._submit_slots.acquire()
                    slot_acquired = time.monotonic()
                try:
                    reply = target.RequestWorkerLease(
                        pb.LeaseRequest(spec=spec))
                except Exception:  # noqa: BLE001 — lease target died
                    if spec.placement_group_id:
                        # Bundle node died: GCS reschedules the bundle;
                        # wait for the new assignment and retry there.
                        time.sleep(0.1)
                        pg_targets = self._pg_lease_targets(spec)
                        target = pg_targets[0]
                        continue
                    if spec.affinity_node_id and not spec.affinity_soft:
                        raise exceptions.RayTpuError(
                            f"Node {spec.affinity_node_id[:8]} died while "
                            f"task {spec.name} was pinned to it")
                    if not self._refresh_local_node():
                        raise exceptions.RayTpuError(
                            "no alive nodes in cluster")
                    target = self.node
                    continue
                if reply.granted:
                    mdefs.LEASE_REQUESTS.inc(tags={"result": "granted"})
                    mdefs.LEASE_LATENCY.observe(
                        time.monotonic() - negotiate_start,
                        tags={"kind": lease_kind})
                    break
                if reply.error == "infeasible":
                    where = ("placement group bundle"
                             if spec.placement_group_id else "cluster node")
                    raise exceptions.RayTpuError(
                        f"Task {spec.name} demands {dict(spec.resources)} "
                        f"which no {where} can ever satisfy.")
                if reply.error == "pg-unknown":
                    # The bundle was rescheduled off this node; re-resolve.
                    time.sleep(0.05)
                    pg_targets = self._pg_lease_targets(spec)
                    target = pg_targets[0]
                    continue
                if reply.error == "pg-wait" and len(pg_targets) > 1:
                    # Any-bundle task: rotate across the group's nodes
                    # before backing off.
                    pg_targets = pg_targets[1:] + pg_targets[:1]
                    target = pg_targets[0]
                if reply.spillback_address:
                    mdefs.LEASE_REQUESTS.inc(tags={"result": "spillback"})
                    target = rpc.get_stub("NodeService",
                                          reply.spillback_address)
                    # Damp spillback ping-pong: nodes with stale views can
                    # bounce a lease between each other (label soft tiers
                    # especially); after a burst of hops, pause long enough
                    # for heartbeats to refresh the views.
                    spillbacks += 1
                    if spillbacks % 8 == 0:
                        time.sleep(min(0.05 * (spillbacks // 8), 0.5))
                    continue
                if time.monotonic() > deadline:
                    raise exceptions.RayTpuError(
                        f"Timed out leasing a worker for {spec.name}")
                mdefs.LEASE_REQUESTS.inc(tags={"result": "retry"})
                time.sleep(backoff)
                # The node queues lease requests server-side for up to 2s,
                # so client retries are rare; a long backoff here would
                # just leave freed workers idle between retries.
                backoff = min(backoff * 1.5, 0.1)
            if reply.tpu_chips:
                del spec.tpu_chips[:]
                spec.tpu_chips.extend(reply.tpu_chips)
            return {"node": target, "worker_id": reply.worker_id,
                    "worker_address": reply.worker_address,
                    "fast_address": reply.worker_fast_address,
                    "tpu_chips": list(reply.tpu_chips)}
        finally:
            self._submit_slots.release()

    def _apply_push_result(self, result: pb.PushTaskResult,
                           return_ids: List[ObjectID], name: str):
        # Values are stored BEFORE the done-marker: a concurrent get that
        # observed "done" with the value still missing would conclude
        # "produced then lost" and re-execute the task spuriously.
        if not result.ok:
            mdefs.TASKS_COMPLETED.inc(tags={"status": "error"})
            err = pickle.loads(result.error) if result.error else \
                exceptions.RayTaskError(name, "task failed")
            self._store_error(err, return_ids)
            if return_ids:
                self._task_done.add(return_ids[0].task_id().binary())
            return
        mdefs.TASKS_COMPLETED.inc(tags={"status": "ok"})
        for i, oid in enumerate(return_ids):
            if i < len(result.in_store) and result.in_store[i]:
                continue  # large result: fetched on demand via the directory
            data = result.inline_results[i]
            self.memory.put(oid, loads_store(data))
            # Inline results flush to the node store + directory LAZILY —
            # only when the ref ESCAPES this process (used as a task arg,
            # pickled into a payload/put): a different worker consuming
            # the return fetches through the directory, but the common
            # case (result get() locally and dropped) never leaves this
            # process, and the eager per-task store put + directory
            # registration was ~30% of the cluster's per-task CPU. A ref
            # that escaped BEFORE the result arrived flushes right now.
            # Order: STORE first, then check escape and pop — a concurrent
            # _flush_escaped (which adds to the set before popping) can
            # then never miss the bytes; the atomic pop decides who
            # flushes.
            ob = oid.binary()
            self._lazy_results[ob] = data
            if ob in self._escaped_ids:
                taken = self._lazy_results.pop(ob, None)
                if taken is not None:
                    self._enqueue_put(("data", oid, taken))
        if return_ids:
            self._task_done.add(return_ids[0].task_id().binary())
        self._complete_pending(return_ids)
        with self._ready_cond:
            self._ready_cond.notify_all()

    def _store_error(self, err, return_ids):
        try:
            blob = dumps(err)
        except Exception:  # noqa: BLE001 — unpicklable error chain
            blob = None
        for oid in return_ids:
            self.memory.put(oid, err)
            if blob is not None:
                self._enqueue_put(("data", oid, blob))
        self._complete_pending(return_ids)
        with self._ready_cond:
            self._ready_cond.notify_all()

    def cancel(self, ref, force, recursive):
        """Cancel a task (reference: ``CoreWorker::CancelTask``,
        ``core_worker.h:961``): pending tasks are dropped at whichever
        dispatch stage holds them (dep-wait, sig queue, lease
        negotiation); running tasks get a CancelTask RPC to their worker
        (async-exc / asyncio cancel; ``force`` kills the worker);
        ``recursive`` propagates through the task's children on the
        executing worker. Finished tasks are untouched (no-op)."""
        self._cancel_task(ref.task_id().binary(), [ref.id().binary()],
                          force, recursive)

    def _task_cancelled(self, tid: bytes) -> bool:
        if not self._cancelled_tasks:
            return False  # lock-free fast path: cancels are rare
        with self._cancel_lock:
            return bytes(tid) in self._cancelled_tasks

    def _store_cancelled(self, spec, return_ids) -> None:
        tid = bytes(spec.task_id)
        self._store_error(
            exceptions.TaskCancelledError(TaskID(tid)), return_ids)
        # Terminal for this task: drop the flag (a long-lived driver
        # cancelling queued tasks forever must not grow the set unboundedly).
        with self._cancel_lock:
            self._cancelled_tasks.discard(tid)

    def _cancel_task(self, tid: bytes, oid_bins: List[bytes], force: bool,
                     recursive: bool) -> None:
        # Already finished? Then it's a no-op — matching the reference:
        # cancel never un-computes a result. _task_done covers
        # store-resident (in_store) results that never touch the local
        # memory store; flagging those would poison a later lineage
        # reconstruction of the same task id.
        if tid in self._task_done:
            return
        if all(self.memory.contains(ObjectID(o)) for o in oid_bins):
            finished = True
            with self._pending_res_lock:
                if any(o in self._pending_results for o in oid_bins):
                    finished = False
            if finished:
                return
        with self._cancel_lock:
            self._cancelled_tasks.add(tid)
            loc = self._running_locs.get(tid)
            children = list(self._children.get(tid, ())) if recursive \
                else []
        if loc:
            try:
                stub = rpc.get_stub("WorkerService", loc)
                stub.CancelTask(pb.CancelTaskRequest(
                    task_id=tid, force=force, recursive=recursive),
                    timeout=10)
            except Exception:  # noqa: BLE001 — worker already gone
                pass
        for ctid, coids in children:
            self._cancel_task(ctid, coids, force, True)

    def cancel_children(self, parent_tid: bytes, force: bool) -> None:
        """Cancel every task the given (locally-executing) task submitted
        — the executor side of a recursive cancel."""
        with self._cancel_lock:
            children = list(self._children.pop(parent_tid, ()))
        for ctid, coids in children:
            self._cancel_task(ctid, coids, force, True)

    def drop_children(self, parent_tid: bytes) -> None:
        with self._cancel_lock:
            self._children.pop(parent_tid, None)

    # ---------------------------------------------------------------- actors
    def create_actor(self, cls, args, kwargs, options) -> ActorID:
        actor_id = ActorID.of(self.job_id)
        demand = dict(options.task_resources())
        payload, contained = dumps_payload((cls, args, kwargs, options))
        from ray_tpu._private.options import resolve_placement

        pf = resolve_placement(options)
        spec = pickle.dumps({
            "resources": demand,
            "runtime_env": self._prepare_runtime_env(
                options.runtime_env or {}),
            "payload": payload,
            # PG-targeted actors are scheduled onto their bundle's node and
            # charge the bundle reservation (gcs_actor_scheduler.cc + PG).
            "pg": ((pf.placement_group_id, pf.bundle_index)
                   if pf.placement_group_id else None),
            "pg_capture": pf.capture_child_tasks,
            # Non-PG strategies for actors (GcsActorScheduler analog):
            # labels/affinity/spread are evaluated by GCS _schedule_actor.
            "labels": pf.label_selector.decode() if pf.label_selector else None,
            "affinity": ((pf.affinity_node_id, pf.affinity_soft)
                         if pf.affinity_node_id else None),
            "strategy": pf.strategy,
        })
        # Constructor args are pinned until the actor reaches a settled
        # state (ALIVE after the constructor's borrow flush, or DEAD):
        # placement can take minutes, during which the caller may drop its
        # only refs (same flight-time rule as submit_task).
        if contained:
            self._flush_escaped(contained)
            for oid in contained:
                self.refs.incr(oid)
            with self._actor_lock:
                self._actor_create_pins[actor_id.binary()] = list(contained)
        info = pb.ActorInfo(
            actor_id=actor_id.binary(),
            name=options.name or "",
            namespace=options.namespace or self.namespace,
            class_name=cls.__name__,
            state="PENDING",
            max_restarts=options.max_restarts,
            spec=spec,
        )
        reply = self.gcs.RegisterActor(pb.RegisterActorRequest(info=info))
        if not reply.ok:
            raise ValueError(reply.error)
        return actor_id

    def _release_create_pins(self, actor_key: bytes) -> None:
        with self._actor_lock:
            pins = self._actor_create_pins.pop(actor_key, None)
        for oid in pins or ():
            self.refs.decr(oid)

    def _resolve_actor(self, actor_id: ActorID,
                       timeout_s: float = 60.0) -> pb.ActorInfo:
        """Resolve an actor's worker address. Pubsub-driven: after one
        initial GetActor (cold cache / missed events), waiters block on the
        ACTOR-channel condition instead of polling the GCS."""
        key = actor_id.binary()
        deadline = time.monotonic() + timeout_s
        checked_gcs = False
        while True:
            with self._actor_lock:
                info = self._actor_cache.get(key)
                dead = self._actor_dead.get(key)
            if info is not None and info.state == "ALIVE":
                return info
            if dead is not None:
                raise exceptions.ActorDiedError(actor_id, dead)
            if not checked_gcs:
                checked_gcs = True
                reply = self.gcs.GetActor(pb.GetActorRequest(actor_id=key))
                if reply.found:
                    if reply.info.state in ("ALIVE", "DEAD"):
                        # Settled: release ctor-arg pins even if the ACTOR
                        # pubsub event was missed.
                        self._release_create_pins(key)
                    if reply.info.state == "ALIVE":
                        with self._actor_lock:
                            self._actor_cache[key] = reply.info
                        return reply.info
                    if reply.info.state == "DEAD":
                        raise exceptions.ActorDiedError(
                            actor_id,
                            reply.info.death_cause or "actor is dead")
                continue
            if time.monotonic() > deadline:
                raise exceptions.GetTimeoutError(
                    f"Actor {actor_id.hex()} not ALIVE within {timeout_s}s")
            with self._ready_cond:
                self._ready_cond.wait(timeout=1.0)
            # Safety: periodically refresh from the GCS in case an ACTOR
            # event was published before our subscription attached.
            checked_gcs = False

    def submit_actor_task(self, actor_id, method_name, args, kwargs, options):
        mdefs.TASKS_SUBMITTED.inc(tags={"kind": "actor"})
        task_id = TaskID.for_actor_task(actor_id)
        streaming = is_streaming(options.num_returns)
        nreturns = 1 if streaming else max(options.num_returns, 1)
        return_ids = [ObjectID.from_task(task_id, i) for i in range(nreturns)]
        # Sequence numbers are scoped to a caller *session*; the session
        # rotates whenever the cached actor address is invalidated, so a
        # restarted actor (fresh ordering state) sees the new session start
        # from 0 while in-flight old-session tasks fail cleanly.
        with self._actor_lock:
            session = self._actor_session.get(actor_id.binary(), 0)
            seq = self._actor_seq.get(actor_id.binary(), 0)
            self._actor_seq[actor_id.binary()] = seq + 1
        if getattr(options, "_is_async_actor", False):
            from ray_tpu._private.concurrency import effective_max_concurrency

            eff = effective_max_concurrency(True, options.max_concurrency)
            st = self._actor_window_state(actor_id.binary())
            st["window"] = max(self.ACTOR_SEND_WINDOW,
                               min(eff, self.ASYNC_ACTOR_SEND_WINDOW_MAX))
        payload, contained = dumps_payload((None, args, kwargs))
        spec = pb.TaskSpec(
            task_id=task_id.binary(),
            name=method_name,
            method_name=method_name,
            return_ids=[oid.binary() for oid in return_ids],
            actor_id=actor_id.binary(),
            sequence_no=seq,
            caller_address=f"{self.worker_id}:{session}".encode(),
            returns_stream=streaming,
        )
        from ray_tpu.util import tracing

        if tracing.enabled():
            tracing.inject_context(spec)
        payload_oid = self._maybe_promote_payload(task_id, payload, spec)
        # Same flight-time pinning as submit_task: actor resolution can take
        # tens of seconds, during which the caller may drop its handles. A
        # promoted payload is pinned the same way (released after the push —
        # actor tasks are not lineage-reconstructed).
        pinned = list(contained)
        self._flush_escaped(contained)
        if payload_oid is not None:
            pinned.append(payload_oid)
        for oid in pinned:
            self.refs.incr(oid)
        self._register_pending(return_ids)
        self._pool.submit(self._push_actor_task, actor_id, spec, return_ids,
                          options.max_task_retries, pinned)
        return [ObjectRef(oid, owner_address=self.node_address)
                for oid in return_ids]

    def _invalidate_actor(self, actor_id: ActorID):
        with self._actor_lock:
            self._actor_cache.pop(actor_id.binary(), None)
            self._actor_session[actor_id.binary()] = \
                self._actor_session.get(actor_id.binary(), 0) + 1
            self._actor_seq[actor_id.binary()] = 0
            st = self._actor_window.get(actor_id.binary())
        if st is not None:
            # New session restarts sequence numbers at 0; reopen the
            # send window so the restarted actor's pushes aren't gated on
            # the dead session's completion counter.
            with st["cond"]:
                st["done"] = 0
                st["cond"].notify_all()

    # Max concurrent pushes per actor. Must stay well under the worker's
    # gRPC server pool: each ordered push occupies a server thread while it
    # waits for its sequence turn, and a full pool with the next-needed
    # sequence still unadmitted is a deadlock (reference analog: the actor
    # scheduling queue admits out-of-order arrivals without holding a
    # thread; this runtime's unary RPCs can't, so the submitter bounds the
    # in-flight window instead).
    ACTOR_SEND_WINDOW = 16
    # Async actors hold a push open for the whole await, so the window IS
    # the concurrency cap seen by one caller — widen it (bounded by the
    # submitter pool of 64 and the worker server pool of 128, shared with
    # gets/prefetches).
    ASYNC_ACTOR_SEND_WINDOW_MAX = 48

    def _actor_window_state(self, aid: bytes) -> dict:
        with self._actor_lock:
            st = self._actor_window.get(aid)
            if st is None:
                st = self._actor_window[aid] = {
                    "cond": threading.Condition(), "done": 0,
                    "window": self.ACTOR_SEND_WINDOW}
            return st

    def _push_actor_task(self, actor_id: ActorID, spec: pb.TaskSpec,
                         return_ids: List[ObjectID], retries: int,
                         pinned: Optional[List[bytes]] = None):
        attempt = 0
        st = self._actor_window_state(actor_id.binary())
        seq = spec.sequence_no
        # Deadline: a session rotation resets the completion counter, so a
        # stale-session push could otherwise wait forever — after the
        # deadline it proceeds and fails fast server-side instead.
        gate_deadline = time.monotonic() + 120.0
        tid = bytes(spec.task_id)
        with st["cond"]:
            while seq >= st["done"] + st["window"] and \
                    not self._shutdown and time.monotonic() < gate_deadline:
                if self._task_cancelled(tid):
                    break
                st["cond"].wait(1.0)
        try:
            if self._task_cancelled(tid):
                # STILL push, as a tombstone: the worker must advance this
                # caller's sequence number or every later task from this
                # caller wedges in wait_turn (ordered actors). The
                # executor sees spec.cancelled and fails the task without
                # running user code.
                spec.cancelled = True
            while True:
                try:
                    info = self._resolve_actor(actor_id)
                    self._running_locs[tid] = info.address
                    result = self._push_fast(info.fast_address, spec)
                    if result is False:
                        # Connection died mid-call: the task MAY have
                        # executed (the frame could have been delivered).
                        # Re-pushing over gRPC here would double-execute
                        # on a still-alive worker; route through the
                        # normal retry gate instead (actor tasks default
                        # to 0 retries for exactly this ambiguity).
                        raise ConnectionError(
                            f"fastpath connection to actor "
                            f"{actor_id.hex()[:12]} lost mid-push")
                    if result is None:
                        stub = rpc.get_stub("WorkerService", info.address)
                        result = stub.PushTask(pb.PushTaskRequest(spec=spec),
                                               timeout=PUSH_TIMEOUT_S)
                    self._apply_push_result(result, return_ids, spec.name)
                    return
                except exceptions.ActorDiedError as e:
                    self._store_error(e, return_ids)
                    return
                except BaseException as e:  # noqa: BLE001
                    self._invalidate_actor(actor_id)
                    # Actor tasks are NOT retried by default (the push may
                    # have executed) — reference: max_task_retries=0.
                    if attempt < retries:
                        attempt += 1
                        time.sleep(0.1)
                        continue
                    self._store_error(
                        exceptions.ActorDiedError(actor_id,
                                                  f"actor task failed: {e}"),
                        return_ids)
                    return
        finally:
            self._running_locs.pop(tid, None)
            if self._cancelled_tasks:
                with self._cancel_lock:
                    self._cancelled_tasks.discard(tid)
            with st["cond"]:
                st["done"] = max(st["done"], seq + 1)
                st["cond"].notify_all()
            for oid in pinned or ():
                self.refs.decr(oid)

    def kill_actor(self, actor_id, no_restart):
        reply = self.gcs.GetActor(
            pb.GetActorRequest(actor_id=actor_id.binary()))
        if not reply.found:
            return
        info = reply.info
        if info.state == "ALIVE" and info.address:
            try:
                rpc.get_stub("WorkerService", info.address).KillActor(
                    pb.KillActorRequest(actor_id=actor_id.binary(),
                                        no_restart=no_restart), timeout=5)
            except Exception:  # noqa: BLE001
                pass
        info.state = "DEAD"
        info.death_cause = "killed via ray_tpu.kill()"
        if no_restart:
            info.max_restarts = 0
        self.gcs.UpdateActor(pb.UpdateActorRequest(info=info))
        with self._actor_lock:
            self._actor_cache.pop(actor_id.binary(), None)

    def get_named_actor(self, name: str, namespace: Optional[str]):
        ns = namespace or self.namespace
        if "/" in name:
            ns, name = name.split("/", 1)
        reply = self.gcs.GetActor(pb.GetActorRequest(name=name, namespace=ns))
        if not reply.found or reply.info.state == "DEAD":
            raise ValueError(
                f"Failed to look up actor {name!r} in namespace {ns!r}")
        info = reply.info
        outer = pickle.loads(info.spec)
        (cls, _args, _kwargs, options), _ = loads_payload(outer["payload"])
        return ActorID(bytes(info.actor_id)), cls, options

    def list_named_actors(self, all_namespaces: bool):
        reply = self.gcs.ListActors(pb.ListActorsRequest(
            namespace=self.namespace, all_namespaces=all_namespaces))
        named = [a for a in reply.actors if a.name and a.state != "DEAD"]
        if all_namespaces:
            return [{"name": a.name, "namespace": a.namespace} for a in named]
        return [a.name for a in named]

    # ---------------------------------------------------------------- misc
    def as_future(self, ref: ObjectRef) -> Future:
        """ObjectRef → Future. Resolution is event-driven for locally
        in-flight tasks: the completion callback fires from the thread
        applying the push result, so a 1k-call async fan-in parks ZERO
        threads (the old poll-per-future design burned a 64-wide pool
        slot per outstanding future — the r5 async-actor parity
        bottleneck). Only refs owned elsewhere fall back to a polling
        thread. Failed tasks resolve the future to their exception
        (matching the local runtime and ``await ref`` semantics)."""
        fut: Future = Future()
        oid = ref.id()

        def resolve_from_store() -> bool:
            try:
                value = self.memory.get_if_ready(oid)
            except KeyError:
                return False
            _future_set(fut, value)
            return True

        if resolve_from_store():
            mdefs.ASYNC_FUTURES.inc(tags={"path": "inline"})
            return fut
        ob = oid.binary()

        def poll():
            try:
                _future_set(fut, self._get_one(ref, None))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        # poll is defined before on_complete can possibly fire: the
        # completion thread may invoke the callback the instant the
        # lock below is released.
        def on_complete():
            if not resolve_from_store():
                # Result lives only in the node store (large, in_store
                # push reply): fetch off-thread.
                self._pool.submit(poll)

        with self._pending_res_lock:
            registered = ob in self._pending_results
            if registered:
                self._pending_callbacks.setdefault(ob, []).append(
                    on_complete)

        if registered:
            mdefs.ASYNC_FUTURES.inc(tags={"path": "callback"})
            return fut
        # Completed between the store check and registration, or owned by
        # another process: the polling path handles both.
        mdefs.ASYNC_FUTURES.inc(tags={"path": "poll"})
        self._pool.submit(poll)
        return fut

    def nodes(self) -> List[Dict[str, Any]]:
        reply = self.gcs.GetNodes(pb.GetNodesRequest())
        return [{
            "NodeID": n.node_id,
            "Alive": n.alive,
            "NodeManagerAddress": n.address,
            "Resources": dict(n.resources),
            "Available": dict(n.available),
            "Labels": dict(n.labels),
            "alive": n.alive,
        } for n in reply.nodes]

    def cluster_resources(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for n in self.gcs.GetNodes(pb.GetNodesRequest()).nodes:
            if not n.alive:
                continue
            for k, v in n.resources.items():
                totals[k] = totals.get(k, 0.0) + v
        return totals

    def available_resources(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for n in self.gcs.GetNodes(pb.GetNodesRequest()).nodes:
            if not n.alive:
                continue
            for k, v in n.available.items():
                totals[k] = totals.get(k, 0.0) + v
        return totals

    # ------------------------------------------------------ placement groups
    def current_placement_group_id(self) -> Optional[bytes]:
        from ray_tpu._private import pg_context

        ctx = pg_context.get()
        return ctx[0] if ctx else None

    def create_placement_group(self, req: pb.CreatePlacementGroupRequest):
        self.gcs.CreatePlacementGroup(req)

    def remove_placement_group(self, group_id: bytes):
        self.gcs.RemovePlacementGroup(
            pb.RemovePlacementGroupRequest(group_id=group_id))

    def get_placement_group(self, group_id: bytes) \
            -> Optional[pb.PlacementGroupInfo]:
        reply = self.gcs.GetPlacementGroup(
            pb.GetPlacementGroupRequest(group_id=group_id))
        return reply.info if reply.found else None

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        # Release this runtime's claim on the process's metric pusher: a
        # disconnected driver must not keep publishing its frozen registry
        # to the live head (the TSDB would stamp those stale series as
        # fresh forever), but a co-resident node manager's claim on the
        # same pusher survives.
        from ray_tpu._private import metrics_pusher, xla_monitor

        metrics_pusher.release_pusher(self.gcs_address)
        # Same story for the XLA plane's capture listener: release this
        # runtime's claim (refcounted — a co-resident node manager's
        # capture plane survives; listeners on dead heads self-reap
        # after repeated stream failures).
        xla_monitor.disconnect(self.gcs_address)
        self._drain_lease_cache()
        try:
            self.refs.shutdown()  # release all held refcounts at the GCS
        except Exception:  # noqa: BLE001
            pass
        stream = getattr(self, "_sub_stream", None)
        if stream is not None:
            try:
                stream.cancel()
            except Exception:  # noqa: BLE001
                pass
        self._pool.shutdown(wait=False)
