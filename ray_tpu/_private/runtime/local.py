"""LocalRuntime: in-process task/actor execution with real future semantics.

Re-design of the reference single-process paths (reference: local mode in
``python/ray/_private/worker.py`` + the CoreWorker task lifecycle in
``src/ray/core_worker/core_worker.cc``): tasks run on a thread pool once their
``ObjectRef`` dependencies are ready (dependency-resolution mirrors
``transport/dependency_resolver.h`` — top-level args are resolved to values,
nested refs are passed through); errors become ``RayTaskError`` values stored
in the task's return objects and re-raised at ``get``; retries honour
``max_retries``/``retry_exceptions`` (reference: ``task_manager.h:212``);
actors are threads with ordered (or concurrent) inboxes mirroring the actor
scheduling queues of ``transport/actor_scheduling_queue.h``.

Resource admission mirrors the raylet's local resource manager
(reference: ``raylet/local_task_manager.cc``): a dispatcher admits queued
tasks only when their resource demand fits the node's available resources,
and — like the reference raylet — a task blocked in ``get()`` temporarily
returns its CPU resources so nested task trees cannot deadlock the node.

This runtime backs single-process usage and is the execution engine unit tests
run against; the cluster runtime reuses its executor pieces worker-side.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import logging
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu import exceptions
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private import options as opt_mod
from ray_tpu._private.options import RemoteOptions
from ray_tpu._private.runtime.interface import CoreRuntime

logger = logging.getLogger(__name__)

_context: contextvars.ContextVar[Optional["_TaskCtx"]] = contextvars.ContextVar(
    "ray_tpu_task_ctx", default=None)


def current_task_context() -> Optional["_TaskCtx"]:
    return _context.get()


class _TaskCtx:
    __slots__ = ("task_id", "actor_id", "attempt", "name", "resources",
                 "ledger")

    def __init__(self, task_id, actor_id=None, attempt=0, name="",
                 resources=None, ledger=None):
        self.task_id = task_id
        self.actor_id = actor_id
        self.attempt = attempt
        self.name = name
        self.resources = resources or {}
        self.ledger = ledger  # bundle ledger for PG tasks; None = main


def _resolve_retry(exc: BaseException, retry_exceptions, retries_left: int) -> bool:
    if retries_left <= 0:
        return False
    if isinstance(exc, exceptions.TaskCancelledError):
        return False
    if retry_exceptions is False:
        # Only system failures are retried by default; in-process execution
        # has no worker crashes, so application errors never retry.
        return False
    if retry_exceptions is True:
        return True
    return isinstance(exc, tuple(retry_exceptions))


class _ResourceLedger:
    """Node-local resource accounting with blocking-release semantics."""

    def __init__(self, total: Dict[str, float]):
        self.total = dict(total)
        self.available = dict(total)
        self.cv = threading.Condition()

    def feasible(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        with self.cv:
            if all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
                for k, v in demand.items():
                    self.available[k] = self.available.get(k, 0.0) - v
                return True
            return False

    def release(self, demand: Dict[str, float]) -> None:
        with self.cv:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) + v
            self.cv.notify_all()

    def snapshot(self) -> Dict[str, float]:
        with self.cv:
            return {k: round(v, 6) for k, v in self.available.items()}


class _LocalActor:
    """An actor instance executing methods on its own thread(s).

    Ordered single-thread execution for ``max_concurrency == 1`` (the
    reference's ordered actor scheduling queue); a small pool when more
    concurrency is requested; an asyncio loop when the class defines any
    coroutine methods (reference: fibers / async actors).
    """

    def __init__(self, runtime: "LocalRuntime", actor_id: ActorID, cls: type,
                 args: tuple, kwargs: dict, options: RemoteOptions):
        self.runtime = runtime
        self.actor_id = actor_id
        self.cls = cls
        self.init_args = args
        self.init_kwargs = kwargs
        self.options = options
        self.instance = None
        self.dead = False
        self.death_cause: Optional[BaseException] = None
        from ray_tpu._private import concurrency as _conc

        # Inherited coroutine (and async-generator) methods count too.
        self.is_async = _conc.class_is_async(cls)
        self.max_concurrency = _conc.effective_max_concurrency(
            self.is_async, options.max_concurrency)
        # Concurrency groups (reference: concurrency_group_manager.h):
        # per-group caps; declaring groups on a sync actor switches it to
        # threaded execution (same rule as the cluster worker).
        self.groups: Dict[str, int] = dict(options.concurrency_groups or {})
        self._inbox: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        t = threading.Thread(target=self._run, name=f"actor-{self.actor_id.hex()[:8]}",
                             daemon=True)
        self._thread = t
        t.start()

    # -- thread bodies ----------------------------------------------------
    def _run(self):
        if getattr(self, "pg_ctx", None) is not None:
            # Capturing PG: the actor thread inherits the group, so the
            # constructor and every (ordered-mode) method schedule children
            # into it (placement_group_capture_child_tasks).
            from ray_tpu._private import pg_context
            pg_context.set(*self.pg_ctx)
        try:
            self.instance = self.cls(*self.init_args, **self.init_kwargs)
        except BaseException as e:  # noqa: BLE001
            self._die(exceptions.RayTaskError.from_exception(
                e, f"{self.cls.__name__}.__init__"))
            return
        self.runtime._actor_started(self.actor_id)
        if self.is_async:
            self._run_async_loop()
        elif self.max_concurrency > 1 or self.groups:
            self._run_concurrent()
        else:
            self._run_ordered()

    def _run_ordered(self):
        while True:
            item = self._inbox.get()
            if item is None:
                return
            self._execute(*item)

    def _group_of(self, method_name: str) -> str:
        from ray_tpu._private import concurrency as _conc

        return _conc.group_of(getattr(self.instance, method_name, None),
                              self.groups)

    def _run_concurrent(self):
        # One pool PER concurrency group, sized to the group's cap (the
        # default group gets max_concurrency) — the pool itself is the
        # gate, so a backlogged group queues in its own executor and can
        # never occupy another group's threads (reference:
        # concurrency_group_manager.h: one BoundedExecutor per group).
        self._group_pools = {
            name: ThreadPoolExecutor(
                max_workers=int(cap),
                thread_name_prefix=f"actor-{self.actor_id.hex()[:6]}-{name}")
            for name, cap in self.groups.items()}
        self._group_pools[""] = self._pool = ThreadPoolExecutor(
            max_workers=self.max_concurrency,
            thread_name_prefix=f"actor-{self.actor_id.hex()[:6]}")
        while True:
            item = self._inbox.get()
            if item is None:
                for pool in self._group_pools.values():
                    pool.shutdown(wait=False)
                return
            try:
                pool = self._group_pools[self._group_of(item[0])]
            except ValueError as e:
                self.runtime._store_error(
                    exceptions.RayTaskError.from_exception(
                        e, f"{self.cls.__name__}.{item[0]}"), item[3])
                continue
            pool.submit(self._execute, *item)

    def _run_async_loop(self):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        sems = {"": asyncio.Semaphore(self.max_concurrency)}
        for name, cap in self.groups.items():
            sems[name] = asyncio.Semaphore(int(cap))

        async def pump():
            while True:
                item = await loop.run_in_executor(None, self._inbox.get)
                if item is None:
                    return
                try:
                    sem = sems[self._group_of(item[0])]
                except ValueError as e:
                    self.runtime._store_error(
                        exceptions.RayTaskError.from_exception(
                            e, f"{self.cls.__name__}.{item[0]}"), item[3])
                    continue

                # Acquire INSIDE the task: a saturated group must not
                # head-of-line block the pump (other groups keep flowing)
                # — same placement as the cluster worker's
                # _run_async_actor_method.
                async def run(item=item, sem=sem):
                    async with sem:
                        await self._execute_async(*item)

                loop.create_task(run())

        try:
            loop.run_until_complete(pump())
        finally:
            try:
                pending = asyncio.all_tasks(loop)
                for t in pending:
                    t.cancel()
                loop.run_until_complete(asyncio.gather(*pending, return_exceptions=True))
            except Exception:
                pass
            loop.close()

    # -- execution --------------------------------------------------------
    def _execute(self, method_name: str, args, kwargs, return_ids: List[ObjectID],
                 task_id: TaskID, streaming: bool = False):
        token = _context.set(_TaskCtx(task_id, self.actor_id,
                                      name=f"{self.cls.__name__}.{method_name}"))
        try:
            if method_name == "__ray_dag_loop__":
                # Compiled-DAG pinned loop (see experimental/channel.py).
                from ray_tpu.experimental.channel import run_dag_loop

                result = run_dag_loop(self.instance, *args)
            else:
                method = getattr(self.instance, method_name)
                result = method(*args, **kwargs)
            if inspect.isgenerator(result):
                self.runtime._store_generator(result, return_ids, task_id,
                                              streaming=streaming)
            elif streaming:
                raise TypeError(
                    f"num_returns='streaming' requires a generator method, "
                    f"but {method_name!r} returned {type(result).__name__}")
            else:
                self.runtime._store_results(result, return_ids)
        except exceptions.AsyncioActorExit:
            self.runtime._store_results(None, return_ids)
            self.terminate()
        except BaseException as e:  # noqa: BLE001
            if self._maybe_simulated_death(e, return_ids):
                return
            if self._maybe_died_in_flight(return_ids):
                return
            err = exceptions.RayTaskError.from_exception(
                e, f"{self.cls.__name__}.{method_name}", task_id)
            self.runtime._store_error(err, return_ids)
        finally:
            _context.reset(token)

    def _maybe_died_in_flight(self, return_ids) -> bool:
        """The actor died OUT FROM UNDER this in-flight call (a
        concurrent task hit a simulated process death and the dying
        event loop cancelled this one): a real process death fails every
        in-flight call with actor death, so the caller must see
        ActorDiedError — not a RayTaskError(CancelledError) that reads
        as a bug in the user method."""
        with self._lock:
            if not self.dead:
                return False
            cause = self.death_cause
        self.runtime._store_error(
            exceptions.ActorDiedError(
                self.actor_id,
                f"Actor {self.actor_id.hex()} died: {cause}"),
            return_ids)
        return True

    def _maybe_simulated_death(self, e: BaseException, return_ids) -> bool:
        """Chaos-injected process kill: the in-process runtime cannot lose
        a real OS process, so the harness raises SimulatedProcessDeath and
        this converts it into genuine actor death — ActorDiedError on the
        in-flight call and every queued one, exactly what a controller
        polling a worker whose host died would observe."""
        from ray_tpu._private import chaos

        if not isinstance(e, chaos.SimulatedProcessDeath):
            return False
        err = exceptions.ActorDiedError(
            self.actor_id,
            f"Actor {self.actor_id.hex()} died: {e.reason}")
        self.runtime._store_error(err, return_ids)
        self._die(err)
        chaos._clear_dying()
        return True

    async def _execute_async(self, method_name, args, kwargs, return_ids,
                             task_id, streaming: bool = False):
        # ContextVar set inside an asyncio task is task-local, so concurrent
        # coroutines keep distinct task contexts.
        token = _context.set(_TaskCtx(task_id, self.actor_id,
                                      name=f"{self.cls.__name__}.{method_name}"))
        try:
            method = getattr(self.instance, method_name)
            result = method(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            if inspect.isasyncgen(result):
                if streaming:
                    from ray_tpu._private.object_ref import \
                        drain_stream_async

                    n = await drain_stream_async(result, task_id,
                                                 self.runtime.store.put)
                    self.runtime._store_results(n, return_ids)
                else:
                    self.runtime._store_results(
                        [item async for item in result], return_ids)
            elif inspect.isgenerator(result):
                self.runtime._store_generator(result, return_ids, task_id,
                                              streaming=streaming)
            elif streaming:
                raise TypeError(
                    f"num_returns='streaming' requires a generator method, "
                    f"but {method_name!r} returned {type(result).__name__}")
            else:
                self.runtime._store_results(result, return_ids)
        except exceptions.AsyncioActorExit:
            self.runtime._store_results(None, return_ids)
            self.terminate()
        except BaseException as e:  # noqa: BLE001
            if self._maybe_simulated_death(e, return_ids):
                return
            if self._maybe_died_in_flight(return_ids):
                return
            err = exceptions.RayTaskError.from_exception(
                e, f"{self.cls.__name__}.{method_name}", task_id)
            self.runtime._store_error(err, return_ids)
        finally:
            _context.reset(token)

    # -- lifecycle --------------------------------------------------------
    def submit(self, method_name, args, kwargs, return_ids, task_id,
               streaming: bool = False):
        with self._lock:
            if self.dead:
                err = exceptions.ActorDiedError(
                    self.actor_id,
                    f"Actor {self.actor_id.hex()} is dead: {self.death_cause}")
                self.runtime._store_error(err, return_ids)
                return
            if (self.options.max_pending_calls >= 0
                    and self._inbox.qsize() >= self.options.max_pending_calls):
                raise exceptions.PendingCallsLimitExceeded(
                    f"Actor {self.actor_id.hex()} has "
                    f">={self.options.max_pending_calls} pending calls")
            self._inbox.put((method_name, args, kwargs, return_ids, task_id,
                             streaming))

    def _die(self, cause: Optional[BaseException]):
        with self._lock:
            if self.dead:
                return
            self.dead = True
            self.death_cause = cause
        # Fail everything still queued, then unblock the worker thread.
        while True:
            try:
                item = self._inbox.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            return_ids = item[3]
            self.runtime._store_error(
                exceptions.ActorDiedError(self.actor_id, f"Actor died: {cause}"),
                return_ids)
        self._inbox.put(None)
        self.runtime._actor_died(self.actor_id, cause)

    def terminate(self, no_restart: bool = True):
        with self._lock:
            if self.dead:
                return
            self.dead = True
        self._inbox.put(None)
        self.runtime._actor_died(self.actor_id, None)


class _AnyBundleLedger:
    """Per-task view over a group's bundle ledgers for bundle_index=-1: the
    acquire picks whichever bundle fits and the release returns to it."""

    def __init__(self, ledgers: Dict[Any, "_ResourceLedger"]):
        self._ledgers = [l for i, l in sorted(ledgers.items())]
        self._charged: Optional[_ResourceLedger] = None
        self.total: Dict[str, float] = {}
        for led in self._ledgers:
            for k, v in led.total.items():
                self.total[k] = max(self.total.get(k, 0.0), v)

    @property
    def dead(self) -> bool:
        return any(getattr(l, "dead", False) for l in self._ledgers)

    def feasible(self, demand: Dict[str, float]) -> bool:
        return any(all(led.total.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()) for led in self._ledgers)

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        if self._charged is not None:
            # Re-acquisition after a blocked-get release sticks to the
            # bundle this task originally charged.
            return self._charged.try_acquire(demand)
        for led in self._ledgers:
            if led.try_acquire(demand):
                self._charged = led
                return True
        return False

    def release(self, demand: Dict[str, float]) -> None:
        if self._charged is not None:
            self._charged.release(demand)

    @property
    def cv(self):
        return (self._charged or self._ledgers[0]).cv


class _PendingTask:  # admission unit; ``ledger=None`` charges the main ledger
    __slots__ = ("fn", "demand", "return_ids", "warned", "ledger")

    def __init__(self, fn, demand, return_ids, ledger=None):
        self.fn = fn
        self.demand = demand
        self.return_ids = return_ids
        self.warned = False
        self.ledger = ledger


class LocalRuntime(CoreRuntime):
    def __init__(self, num_cpus: float = 8, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 node_ip: str = "127.0.0.1"):
        self.job_id = JobID.from_int(1)
        self.node_id = NodeID.from_random()
        self.node_ip = node_ip
        self.store = MemoryStore()
        # Elastic pool: tasks may block on nested get(); true parallelism is
        # limited by resource admission, not pool size.
        self.pool = ThreadPoolExecutor(max_workers=max(64, int(num_cpus) * 8),
                                       thread_name_prefix="task")
        total: Dict[str, float] = {"CPU": float(num_cpus)}
        if num_tpus:
            total["TPU"] = float(num_tpus)
        total.update(resources or {})
        self.ledger = _ResourceLedger(total)
        # Placement groups, single-node edition: a group reserves its summed
        # resources from the main ledger at creation; PG-targeted tasks then
        # charge per-bundle ledgers (bundle_index=-1 charges a group-level
        # ledger — a local-mode simplification of "any bundle").
        self._pgroups: Dict[bytes, Any] = {}
        self._pg_ledgers: Dict[bytes, Dict[Any, _ResourceLedger]] = {}
        self._dispatch_queue: "queue.Queue[Optional[_PendingTask]]" = queue.Queue()
        self._pending: List[_PendingTask] = []
        self._actors: Dict[ActorID, _LocalActor] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._actor_meta: Dict[ActorID, Dict[str, Any]] = {}
        self._cancelled: set = set()
        self._lock = threading.Lock()
        self._shutdown = False
        # Local reference counts: live ObjectRef instances per object. When a
        # count returns to zero the stored value is evicted (single-process
        # analog of the distributed refcount GC).
        self._refcounts: Dict[ObjectID, int] = {}
        self._ref_lock = threading.Lock()
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            name="dispatcher", daemon=True)
        self._dispatcher.start()

    # ------------------------------------------------------------- dispatch
    def _dispatch_loop(self):
        """Admit queued tasks when their resource demand fits (reference:
        ``LocalTaskManager::DispatchScheduledTasksToWorkers``)."""
        while True:
            # Block for new arrivals or a resource release.
            try:
                item = self._dispatch_queue.get(timeout=0.1)
            except queue.Empty:
                item = False  # timeout: re-scan pending (resources may be free)
            if self._shutdown:
                return
            if item is None:
                return
            if item is not False:
                self._pending.append(item)
            still_pending = []
            for t in self._pending:
                led = t.ledger if t.ledger is not None else self.ledger
                if t.ledger is not None and getattr(led, "dead", False):
                    # The task's placement group was removed while it was
                    # queued (cluster analog: pg-unknown lease rejection).
                    self._store_error(
                        exceptions.RayTpuError(
                            "placement group was removed before the task "
                            "could be scheduled"), t.return_ids)
                    continue
                if not led.feasible(t.demand):
                    if not t.warned:
                        t.warned = True
                        logger.warning(
                            "Task demands %s which exceeds total cluster resources"
                            " %s; it will hang until resources are added (parity"
                            " with reference infeasible tasks).",
                            t.demand, led.total)
                    still_pending.append(t)
                elif led.try_acquire(t.demand):
                    self.pool.submit(t.fn)
                else:
                    still_pending.append(t)
            self._pending = still_pending

    def _enqueue(self, fn, demand, return_ids, ledger=None):
        self._dispatch_queue.put(
            _PendingTask(fn, demand, return_ids, ledger=ledger))

    # ---------------------------------------------------------------- objects
    def put(self, value: Any, owner_ref: Optional[ObjectRef] = None) -> ObjectRef:
        ctx = current_task_context()
        task_id = ctx.task_id if ctx else TaskID.for_driver(self.job_id)
        with self._lock:
            oid = ObjectID.from_task(task_id, self._next_put_index())
        self.store.put(oid, value)
        return ObjectRef(oid, owner_address="local")

    _put_index = 0

    def _next_put_index(self) -> int:
        self._put_index += 1
        return 2**31 + (self._put_index % 2**30)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        ctx = current_task_context()
        release = {}
        led = self.ledger
        if ctx is not None and ctx.resources:
            # A task blocked in get() returns its CPU so dependents can run
            # (reference: raylet releases CPU of blocked workers). PG tasks
            # return it to their bundle ledger so same-bundle children can
            # be admitted (the canonical tree-of-tasks-in-a-PG pattern).
            release = {k: v for k, v in ctx.resources.items() if k == "CPU"}
            if ctx.ledger is not None:
                led = ctx.ledger
        if release:
            led.release(release)
            self._dispatch_queue.put(False)
        try:
            deadline = None if timeout is None else time.monotonic() + timeout
            out = []
            for ref in refs:
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                value = self.store.get(ref.id(), remaining)
                if isinstance(value, exceptions.RayTaskError):
                    raise value.as_instanceof_cause()
                if isinstance(value, exceptions.RayTpuError):
                    raise value
                out.append(value)
            return out
        finally:
            if release:
                self._reacquire(release, led)

    def _reacquire(self, demand, ledger=None):
        led = ledger if ledger is not None else self.ledger
        while not led.try_acquire(demand):
            with led.cv:
                led.cv.wait(timeout=0.05)

    def wait(self, refs, num_returns, timeout, fetch_local):
        ids = [r.id() for r in refs]
        ready_ids, _ = self.store.wait(ids, num_returns, timeout)
        ready_set = set(ready_ids)
        ready = [r for r in refs if r.id() in ready_set]
        not_ready = [r for r in refs if r.id() not in ready_set]
        return ready, not_ready

    def free(self, refs):
        self.store.delete([r.id() for r in refs])

    # ------------------------------------------------------------- references
    def add_local_reference(self, ref: ObjectRef) -> None:
        with self._ref_lock:
            self._refcounts[ref.id()] = self._refcounts.get(ref.id(), 0) + 1

    def remove_local_reference(self, object_id: ObjectID) -> None:
        if self._shutdown:
            return
        with self._ref_lock:
            n = self._refcounts.get(object_id, 0) - 1
            if n <= 0:
                self._refcounts.pop(object_id, None)
            else:
                self._refcounts[object_id] = n
        if n == 0:
            self.store.delete([object_id])

    # ---------------------------------------------------------------- tasks
    def submit_task(self, function, function_name, args, kwargs, options):
        from ray_tpu._private import fn_ref as fn_ref_mod

        function = fn_ref_mod.resolve(function)
        task_id = TaskID.for_normal_task(self.job_id)
        nreturns = options.num_returns
        if opt_mod.is_streaming(nreturns):
            nreturns = 1
        return_ids = [ObjectID.from_task(task_id, i) for i in range(max(nreturns, 1))]
        retries = options.max_retries
        if retries is None:
            from ray_tpu._private.config import GLOBAL_CONFIG

            retries = GLOBAL_CONFIG.task_max_retries
        demand = options.task_resources()
        from ray_tpu._private.options import resolve_placement

        pf = resolve_placement(options)
        pg_ctx = ((pf.placement_group_id, pf.bundle_index,
                   pf.capture_child_tasks)
                  if pf.placement_group_id else None)

        def on_ready(rargs, rkwargs):
            def run(ledger=None):
                self._run_task(function, function_name, rargs, rkwargs,
                               return_ids, task_id, retries, options,
                               demand, ledger=ledger, pg_ctx=pg_ctx)

            if pf.placement_group_id:
                # Resolve the bundle ledger off-thread: the group may still
                # be placing (reference: tasks queue on a pending group).
                def admit():
                    try:
                        ledger = self._pg_bundle_ledger(
                            pf.placement_group_id, pf.bundle_index)
                    except BaseException as e:  # noqa: BLE001
                        self._store_error(
                            e if isinstance(e, exceptions.RayTpuError)
                            else exceptions.RayTaskError.from_exception(
                                e, function_name),
                            return_ids)
                        return
                    self._enqueue(lambda: run(ledger), demand, return_ids,
                                  ledger=ledger)

                self.pool.submit(admit)
            else:
                self._enqueue(run, demand, return_ids)

        self._schedule_when_ready(args, kwargs, on_ready, return_ids)
        return [ObjectRef(oid, owner_address="local") for oid in return_ids]

    def _schedule_when_ready(self, args, kwargs, submit, return_ids):
        """Resolve top-level ObjectRef args, then call ``submit``."""
        deps: List[ObjectRef] = [a for a in args if isinstance(a, ObjectRef)]
        deps += [v for v in kwargs.values() if isinstance(v, ObjectRef)]

        def finish(rargs, rkwargs):
            try:
                submit(rargs, rkwargs)
            except BaseException as e:  # noqa: BLE001
                self._store_error(
                    e if isinstance(e, exceptions.RayTpuError)
                    else exceptions.RayTaskError.from_exception(e, "submit"),
                    return_ids)

        if not deps:
            finish(args, kwargs)
            return
        pending = [len(deps)]
        lock = threading.Lock()

        def on_dep(_oid, _value):
            with lock:
                pending[0] -= 1
                if pending[0] != 0:
                    return
            resolved: Dict[ObjectID, Any] = {}
            failed = None
            for d in deps:
                v = self.store.get_if_ready(d.id())
                if isinstance(v, (exceptions.RayTaskError, exceptions.RayTpuError)):
                    failed = v
                resolved[d.id()] = v
            if failed is not None:
                # Dependency failed -> propagate the error without executing.
                self._store_error(failed, return_ids)
                return
            rargs = tuple(resolved[a.id()] if isinstance(a, ObjectRef) else a
                          for a in args)
            rkwargs = {k: (resolved[v.id()] if isinstance(v, ObjectRef) else v)
                       for k, v in kwargs.items()}
            finish(rargs, rkwargs)

        for d in deps:
            self.store.on_ready(d.id(), on_dep)

    def _run_task(self, function, function_name, args, kwargs, return_ids,
                  task_id, retries_left, options, demand, attempt=0,
                  ledger=None, pg_ctx=None):
        retried = False
        try:
            if task_id in self._cancelled:
                self._cancelled.discard(task_id)
                self._store_error(exceptions.TaskCancelledError(task_id), return_ids)
                return
            token = _context.set(_TaskCtx(
                task_id, attempt=attempt, name=function_name,
                resources=demand, ledger=ledger))
            if pg_ctx is not None:
                from ray_tpu._private import pg_context
                pg_context.set(*pg_ctx)
            try:
                result = function(*args, **kwargs)
                if inspect.isgenerator(result):
                    self._store_generator(
                        result, return_ids, task_id,
                        streaming=opt_mod.is_streaming(options.num_returns))
                elif opt_mod.is_streaming(options.num_returns):
                    raise TypeError(
                        f"num_returns='streaming' requires a generator "
                        f"function, but {function_name!r} returned "
                        f"{type(result).__name__}")
                else:
                    self._store_results(result, return_ids)
            except BaseException as e:  # noqa: BLE001
                if _resolve_retry(e, options.retry_exceptions, retries_left):
                    # Resources stay held across the immediate in-place retry.
                    retried = True
                    self.pool.submit(self._run_task, function, function_name,
                                     args, kwargs, return_ids, task_id,
                                     retries_left - 1, options, demand,
                                     attempt + 1, ledger, pg_ctx)
                else:
                    self._store_error(
                        exceptions.RayTaskError.from_exception(
                            e, function_name, task_id),
                        return_ids)
            finally:
                if pg_ctx is not None:
                    from ray_tpu._private import pg_context
                    pg_context.clear()
                _context.reset(token)
        finally:
            if not retried:
                (ledger if ledger is not None else self.ledger).release(demand)
                # Wake the dispatcher so freed resources admit pending tasks.
                self._dispatch_queue.put(False)

    def _store_results(self, result, return_ids: List[ObjectID]):
        n = len(return_ids)
        if n == 1:
            self.store.put(return_ids[0], result)
            return
        if not isinstance(result, (tuple, list)) or len(result) != n:
            err = exceptions.RayTpuError(
                f"Task declared num_returns={n} but returned "
                f"{type(result).__name__} of length "
                f"{len(result) if isinstance(result, (tuple, list)) else 'n/a'}")
            self._store_error(err, return_ids)
            return
        for oid, v in zip(return_ids, result):
            self.store.put(oid, v)

    def _store_generator(self, gen, return_ids: List[ObjectID], task_id,
                         streaming: bool = False):
        if streaming:
            # Each yield becomes its own store object at the deterministic
            # stream id the caller's ObjectRefGenerator polls; the declared
            # return carries the count (ObjectRefStream semantics).
            from ray_tpu._private.object_ref import drain_stream

            self._store_results(
                drain_stream(gen, task_id, self.store.put), return_ids)
            return
        values = list(gen)
        self._store_results(tuple(values) if len(return_ids) > 1 else values,
                            return_ids)

    def release_stream_tail(self, length_ref: ObjectRef,
                            from_index: int) -> None:
        """Delete unconsumed stream items of an abandoned
        ObjectRefGenerator (see ClusterRuntime.release_stream_tail)."""
        task_id = length_ref.task_id()

        def _reap():
            from ray_tpu._private.object_ref import STREAM_INDEX_BASE

            try:
                # Outlast the producer (see ClusterRuntime counterpart).
                while not self._shutdown:
                    ready, _ = self.wait([length_ref], num_returns=1,
                                         timeout=60.0, fetch_local=True)
                    if ready:
                        break
                else:
                    return
                n = int(self.get([length_ref], timeout=30)[0])
            except Exception:  # noqa: BLE001
                # Errored stream: free the contiguous prefix of stored
                # items (see ClusterRuntime.release_stream_tail).
                i = from_index
                while True:
                    oid = ObjectID.from_task(task_id, STREAM_INDEX_BASE + i)
                    if not self.store.contains(oid):
                        return
                    self.store.delete([oid])
                    i += 1
            self.store.delete([
                ObjectID.from_task(task_id, STREAM_INDEX_BASE + i)
                for i in range(from_index, n)])

        threading.Thread(target=_reap, daemon=True,
                         name="stream-reaper").start()

    def _store_error(self, err, return_ids: List[ObjectID]):
        for oid in return_ids:
            self.store.put(oid, err)

    def cancel(self, ref: ObjectRef, force: bool, recursive: bool):
        task_id = ref.task_id()
        if self.store.contains(ref.id()):
            return  # already finished; cancel is a no-op
        self._cancelled.add(task_id)
        # Pending (not yet dispatched) tasks observe the flag in _run_task and
        # store TaskCancelledError; a task already running on a thread cannot
        # be preempted in-process (the cluster runtime force-kills the worker).

    # ---------------------------------------------------------------- actors
    def create_actor(self, cls, args, kwargs, options) -> ActorID:
        actor_id = ActorID.of(self.job_id)
        name = options.name
        ns = options.namespace or "default"
        actor = _LocalActor(self, actor_id, cls, args, kwargs, options)
        from ray_tpu._private.options import resolve_placement

        pf = resolve_placement(options)
        actor.pg_ctx = ((pf.placement_group_id, pf.bundle_index,
                         pf.capture_child_tasks)
                        if pf.placement_group_id else None)
        with self._lock:
            if name:
                key = (ns, name)
                if key in self._named_actors:
                    if options.get_if_exists:
                        return self._named_actors[key]
                    raise ValueError(f"Actor with name {name!r} already exists "
                                     f"in namespace {ns!r}")
                self._named_actors[key] = actor_id
            self._actors[actor_id] = actor
            self._actor_meta[actor_id] = {
                "name": name or "", "namespace": ns, "class_name": cls.__name__,
                "state": "STARTING", "pid": 0,
            }
        actor.start()
        return actor_id

    def _actor_started(self, actor_id):
        with self._lock:
            meta = self._actor_meta.get(actor_id)
            if meta and meta["state"] == "STARTING":
                meta["state"] = "ALIVE"

    def _actor_died(self, actor_id, cause):
        with self._lock:
            meta = self._actor_meta.get(actor_id)
            if meta:
                meta["state"] = "DEAD"
                key = (meta["namespace"], meta["name"])
                if self._named_actors.get(key) == actor_id:
                    del self._named_actors[key]

    def submit_actor_task(self, actor_id, method_name, args, kwargs, options):
        actor = self._actors.get(actor_id)
        task_id = TaskID.for_actor_task(actor_id)
        streaming = opt_mod.is_streaming(options.num_returns)
        nreturns = 1 if streaming else max(options.num_returns, 1)
        return_ids = [ObjectID.from_task(task_id, i) for i in range(nreturns)]
        if actor is None:
            self._store_error(
                exceptions.ActorDiedError(actor_id, "Actor handle is invalid."),
                return_ids)
        else:
            self._schedule_when_ready(
                args, kwargs,
                lambda rargs, rkwargs: actor.submit(method_name, rargs, rkwargs,
                                                    return_ids, task_id,
                                                    streaming),
                return_ids)
        return [ObjectRef(oid, owner_address="local") for oid in return_ids]

    def kill_actor(self, actor_id, no_restart):
        actor = self._actors.get(actor_id)
        if actor is None:
            return
        actor._die(exceptions.ActorDiedError(
            actor_id, f"Actor {actor_id.hex()} was killed via kill()."))

    def get_named_actor(self, name: str, namespace: Optional[str]):
        ns = namespace or "default"
        if "/" in name:
            ns, name = name.split("/", 1)
        with self._lock:
            actor_id = self._named_actors.get((ns, name))
            if actor_id is None:
                raise ValueError(f"Failed to look up actor {name!r} in "
                                 f"namespace {ns!r}")
            actor = self._actors[actor_id]
        return actor_id, actor.cls, actor.options

    def list_named_actors(self, all_namespaces: bool):
        with self._lock:
            if all_namespaces:
                return [{"name": n, "namespace": ns} for ns, n in self._named_actors]
            return [n for ns, n in self._named_actors if ns == "default"]

    def actor_state(self, actor_id: ActorID) -> Dict[str, Any]:
        with self._lock:
            return dict(self._actor_meta.get(actor_id, {}))

    # ---------------------------------------------------------------- misc
    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def cb(_oid, value):
            if isinstance(value, exceptions.RayTaskError):
                fut.set_exception(value.as_instanceof_cause())
            elif isinstance(value, exceptions.RayTpuError):
                fut.set_exception(value)
            else:
                fut.set_result(value)

        self.store.on_ready(ref.id(), cb)
        return fut

    def nodes(self):
        return [{
            "NodeID": self.node_id.hex(),
            "Alive": True,
            "NodeManagerAddress": self.node_ip,
            "Resources": dict(self.ledger.total),
            "alive": True,
        }]

    def cluster_resources(self):
        return dict(self.ledger.total)

    def available_resources(self):
        return self.ledger.snapshot()

    # ------------------------------------------------------ placement groups
    def create_placement_group(self, req):
        """Single-node placement: reserve the group's summed resources from
        the main ledger (async-waiting while busy), then carve per-bundle
        ledgers PG-targeted tasks charge (cluster analog: 2PC + per-bundle
        availability in the node manager)."""
        from ray_tpu.protobuf import ray_tpu_pb2 as pb

        info = pb.PlacementGroupInfo(
            group_id=req.group_id, name=req.name, strategy=req.strategy,
            bundles=list(req.bundles), state="PENDING")
        with self._lock:
            self._pgroups[req.group_id] = info
        total: Dict[str, float] = {}
        for b in req.bundles:
            for k, v in b.resources.items():
                total[k] = total.get(k, 0.0) + v
        infeasible = (
            not self.ledger.feasible(total)
            or (req.strategy == "STRICT_SPREAD" and len(req.bundles) > 1))
        if infeasible:
            info.state = "INFEASIBLE"
            return

        def place():
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and not self._shutdown:
                if self.ledger.try_acquire(total):
                    ledgers: Dict[Any, _ResourceLedger] = {
                        b.index: _ResourceLedger(dict(b.resources))
                        for b in info.bundles}
                    with self._lock:
                        if info.state == "REMOVED":
                            self.ledger.release(total)
                            return
                        self._pg_ledgers[bytes(req.group_id)] = ledgers
                        for b in info.bundles:
                            b.node_id = self.node_id.hex()
                        info.state = "CREATED"
                    return
                time.sleep(0.02)
            if info.state == "PENDING":
                info.state = "INFEASIBLE"

        self.pool.submit(place)

    def remove_placement_group(self, group_id: bytes):
        with self._lock:
            info = self._pgroups.get(group_id)
            if info is None or info.state == "REMOVED":
                return
            was_created = info.state == "CREATED"
            info.state = "REMOVED"
            ledgers = self._pg_ledgers.pop(group_id, None)
        if was_created and ledgers is not None:
            # Return the unconsumed share; charges held by still-running
            # tasks drain into the orphaned bundle ledgers (accepted local-
            # mode simplification — the cluster runtime credits the node).
            # ``dead`` stops the dispatcher from admitting queued PG tasks
            # out of the orphaned ledgers (that capacity was just freed).
            freed: Dict[str, float] = {}
            for led in ledgers.values():
                led.dead = True
                for k, v in led.snapshot().items():
                    freed[k] = freed.get(k, 0.0) + v
            self.ledger.release(freed)
            self._dispatch_queue.put(False)

    def get_placement_group(self, group_id: bytes):
        with self._lock:
            return self._pgroups.get(group_id)

    def current_placement_group_id(self):
        from ray_tpu._private import pg_context

        ctx = pg_context.get()
        return ctx[0] if ctx else None

    def _pg_bundle_ledger(self, group_id: bytes, bundle_index: int) \
            -> _ResourceLedger:
        """Ledger a PG-targeted task charges; blocks while the group places."""
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not self._shutdown:
            with self._lock:
                info = self._pgroups.get(group_id)
                state = info.state if info is not None else None
                ledgers = self._pg_ledgers.get(group_id)
            if info is None:
                raise exceptions.RayTpuError(
                    f"placement group {group_id.hex()[:12]} does not exist")
            if state == "REMOVED":
                raise exceptions.RayTpuError(
                    f"placement group {group_id.hex()[:12]} was removed")
            if state == "INFEASIBLE":
                raise exceptions.RayTpuError(
                    f"placement group {group_id.hex()[:12]} is infeasible")
            if state == "CREATED" and ledgers is not None:
                if bundle_index < 0:
                    return _AnyBundleLedger(ledgers)
                led = ledgers.get(bundle_index)
                if led is None:
                    raise exceptions.RayTpuError(
                        f"bundle index {bundle_index} does not exist in "
                        f"placement group {group_id.hex()[:12]}")
                return led
            time.sleep(0.01)
        raise exceptions.RayTpuError(
            f"timed out waiting for placement group "
            f"{group_id.hex()[:12]} to be placed")

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        self._dispatch_queue.put(None)
        for actor in list(self._actors.values()):
            actor.terminate()
        self.pool.shutdown(wait=False)
